"""AutoencoderKL (the SD/SDXL/FLUX image VAE) — flax.linen, NHWC, TPU-first.

The reference parallelizes only the diffusion network and leaves VAE encode/decode to
its host app (the ComfyUI MODEL wrapper it unwraps at any_device_parallel.py:921-930
is the bare UNet/DiT; latents in, latents out — README.md:199-208 describes the whole
pipeline in latent space). A *standalone* framework has to close that loop itself:
this module is the latents↔pixels stage, so the benchmark ladder's models produce
images without any torch runtime.

TPU-first choices: NHWC throughout (conv-friendly layout), bf16 compute with f32
params, single-head spatial attention in the mid block via the pluggable attention
backend, and a fixed-tile ``decode_tiled`` path (one compiled program reused for every
tile — no dynamic shapes) for images whose full-resolution activations would blow HBM.

Checkpoint layouts covered by models/convert_vae.py: ldm/ComfyUI
(``first_stage_model.*``) for SD1.5/SDXL, and the FLUX ``ae.safetensors`` layout
(same module names, no quant convs, z=16).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import attention_local
from .tiling import blend_mask1d, tile_starts


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    z_channels: int = 4
    base_channels: int = 128
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    norm_groups: int = 32
    # latent = (encode(x) - shift) * scale; decode takes latent / scale + shift.
    scaling_factor: float = 0.18215
    shift_factor: float = 0.0
    # SD-family checkpoints carry 1x1 quant/post_quant convs around the latent;
    # FLUX's ae.safetensors does not.
    use_quant_conv: bool = True
    dtype: Any = jnp.bfloat16


def sd_vae_config(**overrides) -> VAEConfig:
    """SD1.5 kl-f8 VAE (also the SD2.x shape)."""
    return dataclasses.replace(VAEConfig(), **overrides)


def sdxl_vae_config(**overrides) -> VAEConfig:
    return dataclasses.replace(VAEConfig(scaling_factor=0.13025), **overrides)


def sd3_vae_config(**overrides) -> VAEConfig:
    """SD3's 16-channel autoencoder (flux-style module names, no quant convs;
    scale/shift from the SD3 release)."""
    base = VAEConfig(
        z_channels=16,
        scaling_factor=1.5305,
        shift_factor=0.0609,
        use_quant_conv=False,
    )
    return dataclasses.replace(base, **overrides)


def flux_vae_config(**overrides) -> VAEConfig:
    """FLUX/Z-Image 16-channel autoencoder (scale/shift from the flux repo)."""
    base = VAEConfig(
        z_channels=16,
        scaling_factor=0.3611,
        shift_factor=0.1159,
        use_quant_conv=False,
    )
    return dataclasses.replace(base, **overrides)


class VAEResBlock(nn.Module):
    cfg: VAEConfig
    out_ch: int

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype, name="conv1")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="norm2")(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=cfg.dtype, name="nin_shortcut")(x)
        return x + h


class VAEAttnBlock(nn.Module):
    """Single-head full spatial self-attention (the kl-f8 mid-block attention)."""

    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, H, W, C = x.shape
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="norm")(x)
        q = nn.Conv(C, (1, 1), dtype=cfg.dtype, name="q")(h)
        k = nn.Conv(C, (1, 1), dtype=cfg.dtype, name="k")(h)
        v = nn.Conv(C, (1, 1), dtype=cfg.dtype, name="v")(h)
        # (B, H*W, 1 head, C) through the backend-dispatched attention.
        q, k, v = (t.reshape(B, H * W, 1, C) for t in (q, k, v))
        h = attention_local(q, k, v).reshape(B, H, W, C)
        h = nn.Conv(C, (1, 1), dtype=cfg.dtype, name="proj_out")(h)
        return x + h


class Downsample(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        # ldm kl-f8 uses asymmetric (0,1)x(0,1) padding + VALID stride-2 conv.
        x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
        return nn.Conv(
            x.shape[-1], (3, 3), strides=2, padding="VALID",
            dtype=self.cfg.dtype, name="conv",
        )(x)


class Upsample(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
        return nn.Conv(C, (3, 3), padding=1, dtype=self.cfg.dtype, name="conv")(x)


class Encoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Conv(
            cfg.base_channels, (3, 3), padding=1, dtype=cfg.dtype, name="conv_in"
        )(x.astype(cfg.dtype))
        for level, mult in enumerate(cfg.channel_mult):
            ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks):
                h = VAEResBlock(cfg, ch, name=f"down_{level}_block_{i}")(h)
            if level != len(cfg.channel_mult) - 1:
                h = Downsample(cfg, name=f"down_{level}_downsample")(h)
        h = VAEResBlock(cfg, h.shape[-1], name="mid_block_1")(h)
        h = VAEAttnBlock(cfg, name="mid_attn_1")(h)
        h = VAEResBlock(cfg, h.shape[-1], name="mid_block_2")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(
            2 * cfg.z_channels, (3, 3), padding=1, dtype=cfg.dtype, name="conv_out"
        )(h)


class Decoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.cfg
        ch = cfg.base_channels * cfg.channel_mult[-1]
        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype, name="conv_in")(
            z.astype(cfg.dtype)
        )
        h = VAEResBlock(cfg, ch, name="mid_block_1")(h)
        h = VAEAttnBlock(cfg, name="mid_attn_1")(h)
        h = VAEResBlock(cfg, ch, name="mid_block_2")(h)
        for level in reversed(range(len(cfg.channel_mult))):
            ch = cfg.base_channels * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                h = VAEResBlock(cfg, ch, name=f"up_{level}_block_{i}")(h)
            if level != 0:
                h = Upsample(cfg, name=f"up_{level}_upsample")(h)
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="norm_out")(h)
        h = nn.silu(h)
        return nn.Conv(
            cfg.in_channels, (3, 3), padding=1, dtype=cfg.dtype, name="conv_out"
        )(h)


class AutoencoderKL(nn.Module):
    cfg: VAEConfig

    def setup(self):
        cfg = self.cfg
        self.encoder = Encoder(cfg, name="encoder")
        self.decoder = Decoder(cfg, name="decoder")
        if cfg.use_quant_conv:
            self.quant_conv = nn.Conv(
                2 * cfg.z_channels, (1, 1), dtype=cfg.dtype, name="quant_conv"
            )
            self.post_quant_conv = nn.Conv(
                cfg.z_channels, (1, 1), dtype=cfg.dtype, name="post_quant_conv"
            )

    def moments(self, x):
        """Pixels (B,H,W,3 in [-1,1]) → (mean, logvar) of the latent posterior."""
        h = self.encoder(x)
        if self.cfg.use_quant_conv:
            h = self.quant_conv(h)
        mean, logvar = jnp.split(h, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def encode(self, x, rng=None):
        """Pixels → scaled latent. Deterministic (posterior mean) without ``rng``."""
        mean, logvar = self.moments(x)
        z = mean
        if rng is not None:
            z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape, mean.dtype
            )
        return (z - self.cfg.shift_factor) * self.cfg.scaling_factor

    def decode(self, z):
        """Scaled latent → pixels (B, 8H, 8W, 3)."""
        z = z / self.cfg.scaling_factor + self.cfg.shift_factor
        h = z
        if self.cfg.use_quant_conv:
            h = self.post_quant_conv(h)
        return self.decoder(h)

    def __call__(self, x, rng=None):
        return self.decode(self.encode(x, rng))


def vae_output_to_images(decoded: jnp.ndarray) -> jnp.ndarray:
    """Decoder output ([-1, 1] convention) → float images in [0, 1], NHWC — the
    single owner of the output-range convention (pipelines and nodes both use it)."""
    return jnp.clip(decoded * 0.5 + 0.5, 0.0, 1.0)


def images_to_vae_input(images: jnp.ndarray) -> jnp.ndarray:
    """Float images in [0, 1] → the decoder/encoder [-1, 1] convention (inverse
    of ``vae_output_to_images``)."""
    return images * 2.0 - 1.0


def normalize_mask(mask, hw: tuple, method: str = "nearest") -> jnp.ndarray:
    """A MASK wire value in any of its shapes ((H, W) / (B, H, W) /
    (B, H, W, 1)) → float (B, H, W, 1) at the ``hw`` spatial size — the one
    mask-conditioning convention shared by the inpaint nodes (each resizes the
    SAME normalized mask to pixel and latent resolutions)."""
    import jax

    m = jnp.asarray(mask, jnp.float32)
    if m.ndim == 2:
        m = m[None]
    if m.ndim == 3:
        m = m[..., None]
    if m.shape[1:3] != tuple(hw):
        m = jax.image.resize(m, (m.shape[0], *hw, 1), method=method)
    return m


def encode_maybe_tiled(vae, x, tile: int = 0) -> jnp.ndarray:
    """Encode ``x`` through ``vae``, tiled when ``tile > 0`` — the encode-side
    owner of the tile/overlap dispatch policy: overlap = tile/4 floored to the
    VAE's spatial-factor alignment (so any factor-aligned tile size works)."""
    if tile:
        f = vae.spatial_factor
        # Floor BOTH to factor alignment: host widgets/exports carry
        # arbitrary tile sizes (stock accepts any), and encode_tiled
        # rejects unaligned values.
        tile = max(f, tile // f * f)
        # overlap must stay < tile (encode_tiled's contract): a tile floored
        # all the way down to one factor cell runs overlap-free.
        overlap = min(max(f, tile // 4 // f * f), tile - f)
        return vae.encode_tiled(x, tile=tile, overlap=max(0, overlap))
    return vae.encode(x)


def decode_maybe_tiled(vae, z, tile: int = 0) -> jnp.ndarray:
    """Decode ``z`` through ``vae`` (image VAE or VideoVAE), tiled when
    ``tile > 0`` — the single owner of the tile/overlap dispatch policy
    (overlap = tile/4) used by the pipelines and the VAE-decode node."""
    if tile:
        return vae.decode_tiled(z, tile=tile, overlap=tile // 4)
    return vae.decode(z)


@dataclasses.dataclass(frozen=True)
class VAE:
    """The VAE as data: jit-cached encode/decode + weights (mirrors
    api.DiffusionModel's jit-cache-per-entry-point shape so the node layer treats
    both uniformly). Params enter every jitted program as arguments, never as
    baked-in constants."""

    cfg: VAEConfig
    params: Any

    def _jitted(self, method):
        if not hasattr(self, "_jit_cache"):
            object.__setattr__(self, "_jit_cache", {})
        fn = self._jit_cache.get(method)
        if fn is None:
            module = AutoencoderKL(self.cfg)
            fn = self._jit_cache[method] = jax.jit(
                lambda p, *a: module.apply({"params": p}, *a, method=method)
            )
        return fn

    def encode(self, x, rng=None):
        return self._jitted(AutoencoderKL.encode)(self.params, x, rng)

    def decode(self, z):
        return self._jitted(AutoencoderKL.decode)(self.params, z)

    @property
    def spatial_factor(self) -> int:
        """Pixels per latent cell along each spatial dim (8 for the kl-f8 family)."""
        return 2 ** (len(self.cfg.channel_mult) - 1)

    def encode_tiled(self, x, tile: int = 512, overlap: int = 128):
        """Encode in fixed-size overlapping PIXEL tiles (dims in pixels, must be
        multiples of the spatial factor), blending the latent overlaps — the
        img2img counterpart of ``decode_tiled`` for resolutions whose encoder
        activations would blow HBM. Deterministic (posterior mean) only."""
        B, H, W, _ = x.shape
        if H <= tile and W <= tile:
            return self.encode(x)
        f = self.spatial_factor
        if tile % f or overlap % f:
            raise ValueError(f"tile/overlap must be multiples of {f}")
        if not 0 <= overlap < tile:
            raise ValueError(f"need 0 <= overlap < tile, got {overlap=} {tile=}")
        encode = functools.partial(
            self._jitted(AutoencoderKL.encode), self.params
        )
        th, tw = min(tile, H), min(tile, W)
        mask = (
            blend_mask1d(th // f, overlap // f, 1)[:, None]
            * blend_mask1d(tw // f, overlap // f, 1)[None, :]
        )[None, :, :, None]
        out = np.zeros((B, H // f, W // f, self.cfg.z_channels), np.float32)
        weight = np.zeros((1, H // f, W // f, 1), np.float32)
        # Hold the full-resolution pixels on the HOST (like decode_tiled's
        # host accumulation): only one tile's pixels + encoder activations
        # live in HBM at a time.
        x_host = np.asarray(x, np.float32)
        # Window starts computed on the latent grid then scaled back up, so
        # edge tiles (which slide inward) stay f-aligned.
        hs_list = [s * f for s in tile_starts(H // f, th // f, (tile - overlap) // f)]
        ws_list = [s * f for s in tile_starts(W // f, tw // f, (tile - overlap) // f)]
        for hs in hs_list:
            for ws in ws_list:
                enc = np.asarray(
                    encode(x_host[:, hs : hs + th, ws : ws + tw, :], None),
                    np.float32,
                )
                hl, wl = hs // f, ws // f
                out[:, hl : hl + th // f, wl : wl + tw // f] += enc * mask
                weight[:, hl : hl + th // f, wl : wl + tw // f] += mask
        return jnp.asarray(out / weight)

    def decode_tiled(self, z, tile: int = 64, overlap: int = 16):
        """Decode in fixed-size overlapping latent tiles, linearly blending the
        overlaps — bounds decoder activation memory at large resolutions. A cached
        jitted program serves every tile of the same shape (at most two shapes per
        call: interior tiles plus a clamped shape when a dim is shorter than
        ``tile``); edge tiles slide the window back inside the image, never pad."""
        B, H, W, C = z.shape
        if H <= tile and W <= tile:
            return self.decode(z)
        if not 0 <= overlap < tile:
            raise ValueError(f"need 0 <= overlap < tile, got {overlap=} {tile=}")
        f = self.spatial_factor
        stride = tile - overlap
        decode = functools.partial(self._jitted(AutoencoderKL.decode), self.params)
        th, tw = min(tile, H), min(tile, W)
        mask = (
            blend_mask1d(th, overlap, f)[:, None]
            * blend_mask1d(tw, overlap, f)[None, :]
        )[None, :, :, None]
        # Accumulate on the host: the whole point of tiling is that full-resolution
        # buffers don't fit comfortably on-device; only one decoded tile lives in
        # HBM at a time, and the blend (memory-bound, not MXU work) runs in numpy.
        out = np.zeros((B, H * f, W * f, self.cfg.in_channels), np.float32)
        weight = np.zeros((1, H * f, W * f, 1), np.float32)
        for hs in tile_starts(H, th, stride):
            for ws in tile_starts(W, tw, stride):
                dec = np.asarray(
                    decode(z[:, hs : hs + th, ws : ws + tw, :]), np.float32
                )
                out[:, hs * f : (hs + th) * f, ws * f : (ws + tw) * f] += dec * mask
                weight[:, hs * f : (hs + th) * f, ws * f : (ws + tw) * f] += mask
        return jnp.asarray(out / weight)


def build_vae(cfg: VAEConfig, rng=None, params=None, sample_hw: int = 32) -> VAE:
    """Initialize (or wrap pre-converted ``params`` from convert_vae) a VAE."""
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        module = AutoencoderKL(cfg)
        x = jnp.zeros((1, sample_hw, sample_hw, cfg.in_channels), jnp.float32)
        params = module.init(rng, x)["params"]
    return VAE(cfg=cfg, params=params)
