"""Checkpoint loading: safetensors file → converted params → ready DiffusionModel.

The reference leaves model loading to its host app and replicates the already-loaded
torch module (SURVEY §5.4); standalone, this framework needs the load path itself:

    model = load_flux_checkpoint("flux1-schnell.safetensors", flux_schnell_config())
    pm = parallelize(model, chain)

Design points:

- **No wasted init.** ``flax.Module.init`` on a FLUX-scale model allocates and
  initializes billions of parameters just to throw them away. The builders here
  construct the module + metadata (block lists, pipeline spec) and attach the
  converted checkpoint params directly.
- LoRA merges *before* conversion (``bake_lora``) — the analogue of the reference's
  bake-before-replicate (any_device_parallel.py:992-1004).
- fp8/bf16-stored tensors upcast on read (93-124/688-699 parity lives in
  convert.to_numpy); safetensors handles the raw dtypes via ml_dtypes.
"""

from __future__ import annotations

import os
from collections.abc import Mapping
from typing import Any

import numpy as np

from ..utils.logging import get_logger
from .api import DiffusionModel
from .convert import bake_lora, convert_flux_checkpoint, to_numpy
from .convert_unet import convert_sd_unet_checkpoint, strip_prefix
from .flux import FluxConfig, build_flux
from .unet import UNetConfig, build_unet
from .wan import WanConfig, build_wan


def params_nbytes(params) -> int:
    """Total stored bytes of a parameter pytree (QuantTensor int8 leaves count
    at their stored width — the number that competes for HBM)."""
    import jax

    return sum(
        int(l.size) * l.dtype.itemsize for l in jax.tree.leaves(params)
    )


def pin_params_host(params, device=None):
    """Host-resident placement for the weight-streaming executor
    (parallel/streaming.py): every leaf lands in the device's ``pinned_host``
    memory space where the backend supports memory kinds (TPU — DMA-able
    pages, so the per-stage host→HBM prefetch runs at full PCIe/ICI rate
    without a bounce copy), and falls back to plain host numpy arrays
    otherwise (CPU backend, older runtimes). Either way the returned pytree
    holds NO device-memory footprint — stage sub-pytrees are carved from it
    and streamed per call."""
    import jax

    from ..parallel.mesh import streamed_tree_put

    dev = device if device is not None else jax.devices()[0]
    try:
        sharding = jax.sharding.SingleDeviceSharding(
            dev, memory_kind="pinned_host"
        )
        # Probe with one tiny transfer before committing the whole pytree —
        # backends without pinned_host raise here, not at tree scale.
        jax.block_until_ready(jax.device_put(np.zeros((1,)), sharding))
        return streamed_tree_put(params, lambda _: sharding)
    except Exception:
        get_logger().info(
            "pinned_host memory kind unavailable on %s; keeping weights as "
            "host numpy arrays", getattr(dev, "platform", dev),
        )
        return jax.tree.map(np.asarray, params)


def carve_ranges(sizes: "list[int] | tuple[int, ...]",
                 max_stage_bytes: int | None = None,
                 n_stages: int | None = None) -> list[tuple[int, int]]:
    """The pure carve arithmetic behind :func:`carve_stages`, over segment
    byte sizes alone (no params pytree, no jax) — shared with the
    auto-parallel planner (parallel/planner.py), whose stream-carve
    candidates are exactly this function at different caps/counts. Greedy
    contiguous packing: each stage's bytes fit ``max_stage_bytes`` (half the
    double-buffer budget), or — when only a stage COUNT is given — stages
    are balanced by bytes. Single-segment stages may exceed the byte cap (a
    segment is the atomic streaming unit — the cap then simply degrades to
    one-segment-at-a-time streaming)."""
    sizes = list(sizes)
    total = sum(sizes)
    if max_stage_bytes is None:
        n = max(1, min(len(sizes), int(n_stages or 4)))
        max_stage_bytes = max(1, -(-total // n))
    ranges: list[tuple[int, int]] = []
    start, acc = 0, 0
    for i, sz in enumerate(sizes):
        if i > start and acc + sz > max_stage_bytes:
            ranges.append((start, i))
            start, acc = i, 0
        acc += sz
    ranges.append((start, len(sizes)))
    return ranges


def segment_nbytes(spec, params) -> list[int]:
    """Per-segment parameter bytes of a ``PipelineSpec`` — the byte profile
    the carve (and the planner's stage-carve search) operates on."""
    return [
        params_nbytes({k: params[k] for k in seg.param_keys})
        for seg in spec.segments
    ]


def carve_stages(spec, params, max_stage_bytes: int | None = None,
                 n_stages: int | None = None) -> list[tuple[int, int]]:
    """Partition a ``PipelineSpec``'s segments into contiguous stage ranges
    for the streaming executor: each stage's parameter sub-pytree fits
    ``max_stage_bytes`` (half the double-buffer budget), or — when only a
    stage COUNT is given — stages are balanced by bytes. Returns
    ``[(start, end), ...]`` over ``spec.segments``; see :func:`carve_ranges`
    for the oversized-single-segment caveat."""
    return carve_ranges(
        segment_nbytes(spec, params),
        max_stage_bytes=max_stage_bytes, n_stages=n_stages,
    )


def load_safetensors(path: str | os.PathLike) -> dict[str, np.ndarray]:
    """Read every tensor of a .safetensors file into float32 numpy.

    bf16/f16/fp8-stored tensors upcast here (the conversion dtype policy); the
    model's compute dtype re-casts at apply time.
    """
    from safetensors import safe_open

    out: dict[str, np.ndarray] = {}
    with safe_open(os.fspath(path), framework="numpy") as f:
        for key in f.keys():
            t = f.get_tensor(key)
            out[key] = np.asarray(t, dtype=np.float32) if t.dtype != np.float32 else t
    return out


def _resolve_state_dict(src: Any) -> dict[str, Any]:
    """Accept a path to .safetensors or an in-memory {name: tensor} mapping."""
    if isinstance(src, (str, os.PathLike)):
        return load_safetensors(src)
    if isinstance(src, Mapping):
        return dict(src)
    raise TypeError(f"expected a path or state dict, got {type(src).__name__}")


def peek_safetensors(path: str | os.PathLike) -> dict[str, Any]:
    """Key → shape-only stub for every tensor in a .safetensors file, WITHOUT
    reading tensor data (header metadata only). Enough for
    ``sniff_model_family``; a multi-GB checkpoint costs one header read."""
    import types

    from safetensors import safe_open

    with safe_open(os.fspath(path), framework="numpy") as f:
        return {
            k: types.SimpleNamespace(shape=tuple(f.get_slice(k).get_shape()))
            for k in f.keys()
        }


def load_safetensors_subset(
    path: str | os.PathLike, *prefixes: str
) -> dict[str, np.ndarray]:
    """Read only the keys under the given prefixes (e.g. the bundled
    ``cond_stage_model.`` text tower) — the rest of the file is never
    materialized."""
    from safetensors import safe_open

    out: dict[str, np.ndarray] = {}
    with safe_open(os.fspath(path), framework="numpy") as f:
        for key in f.keys():
            if any(key.startswith(p) for p in prefixes):
                t = f.get_tensor(key)
                out[key] = (
                    np.asarray(t, dtype=np.float32)
                    if t.dtype != np.float32 else t
                )
    return out


def _maybe_bake(sd: dict, lora: Any, strength: float) -> dict:
    """Bake one LoRA — or a STACK: ``lora`` may be a list of ``(lora, strength)``
    pairs, applied in order (the stock LoraLoader chain; each shim link appends
    to the list and the whole stack re-bakes from the source checkpoint)."""
    if lora is None:
        return sd
    stack = lora if isinstance(lora, (list, tuple)) else [(lora, strength)]
    for item in stack:
        src_i, s_i = item if isinstance(item, (list, tuple)) else (item, strength)
        lora_sd = _resolve_state_dict(src_i)
        get_logger().info(
            "baking LoRA (%d tensors, strength %.2f)", len(lora_sd), s_i
        )
        sd = bake_lora(sd, lora_sd, s_i)
    return sd


def load_flux_checkpoint(
    src: Any,
    cfg: FluxConfig,
    lora: Any = None,
    lora_strength: float = 1.0,
    name: str = "flux",
) -> DiffusionModel:
    """FLUX checkpoint (path or state dict, official BFL layout) → DiffusionModel."""
    sd = _maybe_bake(_resolve_state_dict(src), lora, lora_strength)
    return build_flux(cfg, name=name, params=convert_flux_checkpoint(sd, cfg))


def load_sd_unet_checkpoint(
    src: Any,
    cfg: UNetConfig,
    lora: Any = None,
    lora_strength: float = 1.0,
    name: str = "sd-unet",
) -> DiffusionModel:
    """SD1.5/SDXL checkpoint → DiffusionModel. Accepts full ComfyUI checkpoints
    (``model.diffusion_model.*`` subtree selected automatically) or bare UNet dicts."""
    sd = strip_prefix(_resolve_state_dict(src))
    sd = _maybe_bake(sd, lora, lora_strength)
    return build_unet(cfg, name=name, params=convert_sd_unet_checkpoint(sd, cfg))


def load_controlnet_checkpoint(
    src: Any,
    cfg: "UNetConfig | None" = None,
    name: str = "controlnet",
) -> DiffusionModel:
    """ControlNet checkpoint (ldm single-file layout — bare keys or the
    ``control_model.`` prefix some exports carry — or the diffusers
    ``ControlNetModel`` layout most public SDXL controlnets ship in, detected
    by its ``controlnet_cond_embedding.*`` keys and remapped) → a ControlNet
    DiffusionModel for ``apply_control``. With ``cfg=None`` the base-UNet
    family is sniffed off the cross-attention context width (768 → sd15,
    1024 → sd21, 2048/label_emb → sdxl). Loading either layout is host
    behavior the reference assumes (its unwrap, any_device_parallel.py:921-930,
    is agnostic to how the control model got into the MODEL it wraps)."""
    from .controlnet import build_controlnet
    from .convert_unet import (
        convert_controlnet_checkpoint,
        diffusers_controlnet_to_ldm,
    )

    sd = dict(_resolve_state_dict(src))
    if any(k.startswith("control_model.") for k in sd):
        sd = strip_prefix(sd, "control_model.")
    if any(k.startswith("controlnet_cond_embedding.") for k in sd):
        sd = diffusers_controlnet_to_ldm(sd)
    if cfg is None:
        # Package-level attrs (not .unet directly): the node layer resolves
        # configs through the package namespace everywhere else, and tests
        # shrink models by monkeypatching exactly these names.
        from . import sd15_config, sd21_config, sdxl_config

        key = next(
            (k for k in sd if k.endswith("attn2.to_k.weight")
             and k.startswith("input_blocks.")), None,
        )
        ctx = int(to_numpy(sd[key]).shape[1]) if key else 768
        if any(k.startswith("label_emb.") for k in sd) or ctx == 2048:
            cfg = sdxl_config()
        elif ctx == 1024:
            cfg = sd21_config()
        else:
            cfg = sd15_config()
    return build_controlnet(
        cfg, name=name, params=convert_controlnet_checkpoint(sd, cfg)
    )


def sniff_model_family(state_dict: Mapping[str, Any]) -> str:
    """Model family id (nodes._MODEL_FAMILIES vocabulary) from checkpoint key
    signatures — the stock ``CheckpointLoaderSimple`` has no family widget, so
    the compat shim (nodes_compat.py) sniffs it off the file the way the host
    loader the reference defers to does. Keys may be bare or under the full
    checkpoint's ``model.diffusion_model.`` prefix."""
    pfx = "model.diffusion_model."
    names = {k[len(pfx):] if k.startswith(pfx) else k: k for k in state_dict}

    def has(prefix: str) -> bool:
        return any(n.startswith(prefix) for n in names)

    def dim(name: str, axis: int) -> int | None:
        key = names.get(name)
        if key is None:
            return None
        shape = getattr(state_dict[key], "shape", None)
        return None if shape is None else int(shape[axis])

    if has("double_blocks."):
        if has("guidance_in."):
            return "flux-dev"
        depth = 1 + max(
            int(n.split(".")[1]) for n in names if n.startswith("double_blocks.")
        )
        # No guidance embed: schnell runs the full 19-double-block stack; the
        # z-image proxy (flux.py z_image_turbo_config, depth 6/26) is the
        # shallow single-stream-dominant point of the family.
        return "flux-schnell" if depth >= 12 else "zimage-turbo"
    if has("joint_blocks."):
        if any(".x_block.attn2." in n for n in names):
            return "sd35-medium"  # dual-attention mmdit-x
        depth = 1 + max(
            int(n.split(".")[1]) for n in names if n.startswith("joint_blocks.")
        )
        return "sd35-large" if depth >= 38 else "sd3-medium"
    if has("blocks.0.self_attn.") or has("blocks.0.cross_attn."):
        width = dim("blocks.0.self_attn.q.weight", 0)
        return "wan-14b" if width is not None and width >= 5120 else "wan-1.3b"
    if has("input_blocks."):
        # 9 input channels (latent 4 + mask 1 + masked-image latent 4) mark
        # the dedicated inpainting variants of the SD families.
        in_ch = dim("input_blocks.0.0.weight", 1)
        inpaint = "-inpaint" if in_ch == 9 else ""
        if has("label_emb."):
            # SD2.1-unCLIP also carries an adm label_emb, but keeps the SD2
            # block layout (a transformer at input_blocks.1 with OpenCLIP-H
            # 1024-wide context; SDXL's first attention sits deeper and its
            # context is 2048; the SDXL REFINER's sits deeper still and is
            # OpenCLIP-G-only, 1280-wide).
            ctx = dim("input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight", 1)
            if ctx == 1024:
                return "sd21-unclip"
            first_attn = next(
                (n for n in sorted(names)
                 if n.endswith("transformer_blocks.0.attn2.to_k.weight")
                 and n.startswith("input_blocks.")), None,
            )
            if first_attn is not None and dim(first_attn, 1) == 1280:
                return "sdxl-refiner"
            return "sdxl" + inpaint
        ctx = dim("input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight", 1)
        # 768 = CLIP-L (SD1.x); 1024 = OpenCLIP-H (SD2.x). eps-vs-v prediction
        # is not recorded in weights, so SD2.x defaults to the eps preset —
        # pass family explicitly (TPUCheckpointLoader) for v-prediction models.
        if ctx == 768 and inpaint:
            return "sd15-inpaint"
        if ctx == 1024 and inpaint:
            return "sd21-inpaint"
        if inpaint:
            raise ValueError(
                "9-channel (inpainting) checkpoint with an unrecognized "
                f"context width {ctx} — supported inpaint families: "
                "sd15-inpaint, sd21-inpaint, sdxl-inpaint"
            )
        if ctx == 1024:
            # The most common SD2.1 checkpoint (768-v) is v-prediction; with
            # the eps preset it silently produces garbage images. Make the
            # default diagnosable at load time instead of debuggable at
            # render time.
            get_logger().warning(
                "SD2.x checkpoint sniffed as 'sd21' (eps-prediction). If this "
                "is a v-prediction model (e.g. the common 768-v checkpoint), "
                "pass family='sd21-v' via TPUCheckpointLoader or images will "
                "be garbage."
            )
            return "sd21"
        return "sd15"
    raise ValueError(
        "cannot sniff model family: no known diffusion-model key signature "
        "(double_blocks/joint_blocks/self_attn/input_blocks) in checkpoint"
    )


def sniff_vae_config(state_dict: Mapping[str, Any]):
    """Pick a VAE family config from checkpoint weights: ``flux_vae_config()`` for a
    16-channel latent, ``sd_vae_config()`` for 4 channels (read off
    ``decoder.conv_in``, prefixed layouts handled). SD1.5 vs SDXL VAEs are
    weight-shape identical but need different scaling factors — the 4-channel default
    warns and SDXL users should pass ``sdxl_vae_config()`` explicitly."""
    from .convert_vae import strip_vae_prefix
    from .vae import flux_vae_config, sd_vae_config

    sd = strip_vae_prefix(state_dict)  # single owner of the prefix vocabulary
    if "decoder.conv_in.weight" not in sd:
        raise KeyError("decoder.conv_in.weight not found — not an AutoencoderKL dict")
    conv_in = to_numpy(sd["decoder.conv_in.weight"])
    z_ch = conv_in.shape[1] if conv_in.ndim == 4 else conv_in.shape[-1]
    if z_ch == 16:
        return flux_vae_config()
    get_logger().warning(
        "4-channel VAE: defaulting to sd_vae_config() (scaling 0.18215); "
        "SDXL VAEs are shape-identical but need sdxl_vae_config() "
        "(scaling 0.13025) — pass cfg= explicitly for SDXL"
    )
    return sd_vae_config()


def load_vae_checkpoint(
    src: Any,
    cfg: "VAEConfig | None" = None,
):
    """AutoencoderKL checkpoint → VAE. Accepts a standalone vae/ae.safetensors, a
    full ComfyUI checkpoint (``first_stage_model.*`` selected automatically), or an
    in-memory state dict. ``cfg`` defaults via ``sniff_vae_config`` (prefer passing
    it explicitly for SDXL)."""
    from .convert_vae import convert_vae_checkpoint
    from .vae import build_vae

    sd = _resolve_state_dict(src)
    if cfg is None:
        cfg = sniff_vae_config(sd)
    # convert_vae_checkpoint owns the prefix strip — no pre-strip here.
    return build_vae(cfg, params=convert_vae_checkpoint(sd, cfg))


def load_clip_text_checkpoint(src: Any, cfg=None, open_clip: bool = False):
    """CLIP text tower checkpoint → TextEncoder. ``open_clip=True`` selects the
    OpenCLIP resblocks layout (SDXL's second encoder); default is the HF
    ``text_model.*`` layout (SD1.5 / SDXL first encoder / FLUX clip_l)."""
    from .convert_text import (
        convert_clip_text_checkpoint,
        convert_open_clip_checkpoint,
    )
    from .text_encoders import build_clip_text, clip_l_config, open_clip_g_config

    sd = _resolve_state_dict(src)
    if cfg is None:
        cfg = open_clip_g_config() if open_clip else clip_l_config()
    convert = convert_open_clip_checkpoint if open_clip else convert_clip_text_checkpoint
    return build_clip_text(cfg, params=convert(sd, cfg))


def load_t5_checkpoint(src: Any, cfg=None):
    """T5 encoder checkpoint (HF layout) → TextEncoder (FLUX/WAN t5xxl)."""
    from .convert_text import convert_t5_checkpoint
    from .text_encoders import build_t5_encoder, t5_xxl_config

    sd = _resolve_state_dict(src)
    if cfg is None:
        cfg = t5_xxl_config()
    return build_t5_encoder(cfg, params=convert_t5_checkpoint(sd, cfg))


def load_wan_checkpoint(
    src: Any,
    cfg: WanConfig,
    lora: Any = None,
    lora_strength: float = 1.0,
    params_converter=None,
    name: str = "wan",
) -> DiffusionModel:
    """WAN checkpoint → DiffusionModel. The official Wan2.x layout converts via
    ``convert_wan_checkpoint`` by default (with ``lora`` baked before
    conversion, like the other families); pass ``params_converter``
    (state_dict, cfg) -> params for repacked layouts, or a pre-converted param
    pytree as ``src`` (lora is not supported for pre-converted pytrees)."""
    import jax

    if params_converter is not None:
        params = params_converter(
            _maybe_bake(dict(_resolve_state_dict(src)), lora, lora_strength), cfg
        )
    elif isinstance(src, Mapping) and not any("." in k for k in src):
        if lora is not None:
            raise ValueError(
                "lora baking needs the flat checkpoint layout; pass the "
                "state dict / file instead of a pre-converted param pytree"
            )
        # Pre-converted nested pytree: apply the float32 upcast policy to every
        # leaf (bf16/fp8 storage dtypes included), same as the file-load path.
        params = jax.tree.map(to_numpy, src)
    else:
        from .convert_wan import convert_wan_checkpoint

        try:
            params = convert_wan_checkpoint(
                _maybe_bake(dict(_resolve_state_dict(src)), lora, lora_strength),
                cfg,
            )
        except KeyError as e:
            raise ValueError(
                f"state dict is not the official Wan2.x layout (missing {e}); "
                "pass params_converter=(state_dict, cfg) -> params for repacked "
                "layouts, or a pre-converted param pytree"
            ) from e
    return build_wan(cfg, name=name, params=params)


def load_wan_vae_checkpoint(src: Any, cfg=None):
    """WAN video-VAE checkpoint (official Wan2.x_VAE layout, optionally wrapped
    under a ``vae.``/``first_stage_model.`` prefix) → VideoVAE."""
    from .convert_wan_vae import convert_wan_vae_checkpoint
    from .video_vae import build_video_vae, wan_vae_config

    sd = _resolve_state_dict(src)
    for prefix in ("vae.", "first_stage_model.", "model."):
        stripped = {
            k[len(prefix):]: v for k, v in sd.items() if k.startswith(prefix)
        }
        if any(k.startswith("encoder.conv1.") for k in stripped):
            sd = stripped
            break
    if cfg is None:
        cfg = wan_vae_config()
    try:
        params = convert_wan_vae_checkpoint(sd, cfg)
    except KeyError as e:
        raise ValueError(
            f"state dict is not the official Wan2.x VAE layout (missing {e})"
        ) from e
    return build_video_vae(cfg, params=params)


def load_mmdit_checkpoint(src: Any, cfg, lora: Any = None,
                          lora_strength: float = 1.0, name: str = "mmdit"):
    """SD3/SD3.5 MMDiT checkpoint (SAI/ComfyUI single-file, optionally under
    model.diffusion_model.) → DiffusionModel."""
    from .convert_mmdit import convert_mmdit_checkpoint, strip_mmdit_prefix
    from .mmdit import build_mmdit

    sd = strip_mmdit_prefix(_resolve_state_dict(src))
    sd = _maybe_bake(sd, lora, lora_strength)
    # Dual-attention layout (SD3.5-medium mmdit-x) and q/k RMS norm presence are
    # facts of the checkpoint — align the config to what the state dict actually
    # contains so a caller passing a generic config still loads correctly (the
    # converter itself stays strict on both).
    attn2_layers = tuple(sorted(
        int(k.split(".")[1])
        for k in sd
        if k.startswith("joint_blocks.") and k.endswith(".x_block.attn2.qkv.weight")
    ))
    has_qk_norm = any(
        k.startswith("joint_blocks.") and k.endswith(".attn.ln_q.weight") for k in sd
    )
    if (
        attn2_layers != tuple(cfg.x_block_self_attn_layers)
        or has_qk_norm != cfg.qk_norm
    ):
        import dataclasses

        from ..utils.logging import get_logger

        get_logger().info(
            "aligning MMDiT config to checkpoint: dual-attention layers %s, "
            "qk_norm=%s", list(attn2_layers), has_qk_norm,
        )
        cfg = dataclasses.replace(
            cfg, x_block_self_attn_layers=attn2_layers, qk_norm=has_qk_norm
        )
    return build_mmdit(cfg, name=name, params=convert_mmdit_checkpoint(sd, cfg))
