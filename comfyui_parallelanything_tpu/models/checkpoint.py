"""Native parameter save/restore (orbax) — SURVEY §5.4.

The reference never saves state (state_dict is only an in-memory transfer format
during cloning, any_device_parallel.py:616/639-665) and leans on its host app for
model files. This framework hosts models itself (models/loader.py reads the torch
ecosystem's safetensors), so it also carries a native round-trip format for
converted params: orbax checkpoints skip the torch→flax conversion on every
subsequent load and restore directly into any sharding.
"""

from __future__ import annotations

import os
from typing import Any


def save_params(path: str | os.PathLike, params: Any) -> None:
    """Write a parameter pytree to an orbax checkpoint directory."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.fspath(os.path.abspath(path)), params)


def load_params(path: str | os.PathLike, like: Any | None = None) -> Any:
    """Restore a parameter pytree.

    ``like`` (optional) is an abstract/concrete pytree whose structure, dtypes and
    *shardings* the restore targets — pass e.g. ``jax.eval_shape`` output with
    `NamedSharding`s to restore directly into a mesh placement without a host copy.
    """
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if like is None:
            return ckptr.restore(os.fspath(os.path.abspath(path)))
        return ckptr.restore(os.fspath(os.path.abspath(path)), like)
