"""AutoencoderKL checkpoint (ldm/ComfyUI/FLUX layout) → models/vae.py param tree.

Covers the ``first_stage_model.*`` subtree of a ComfyUI full checkpoint, standalone
``vae.safetensors`` files (same names, no prefix), and FLUX ``ae.safetensors``
(identical module names, no quant convs, z=16). The reference leaves the VAE to its
host app entirely (it only ever touches the bare diffusion model,
any_device_parallel.py:921-930); this converter is part of making the TPU framework
standalone. Conversion conventions match convert.py: fp8/bf16/fp16 upcast to f32,
torch OIHW conv weights → flax HWIO, rank-2 attention projections (diffusers-style
exports) accepted next to the ldm rank-4 1×1 convs.

ldm → here structural map (module names in models/vae.py are explicit, so the flax
tree mirrors these directly):

- ``{enc,dec}oder.conv_in/conv_out/norm_out``       → same names
- ``encoder.down.{l}.block.{i}.*``                  → ``encoder/down_{l}_block_{i}``
- ``encoder.down.{l}.downsample.conv``              → ``encoder/down_{l}_downsample``
- ``{enc,dec}oder.mid.block_{1,2}``, ``mid.attn_1`` → ``mid_block_{1,2}``, ``mid_attn_1``
- ``decoder.up.{l}.block.{i}`` / ``up.{l}.upsample``→ ``decoder/up_{l}_block_{i}`` /
  ``decoder/up_{l}_upsample`` (ldm's ``up`` list is indexed by resolution level,
  executed high→low, same as models/vae.py's reversed loop)
- ``quant_conv`` / ``post_quant_conv``              → same names (when cfg.use_quant_conv)
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np

from .convert import conv_kernel, to_numpy, tree_to_jnp
from .vae import VAEConfig


def _conv(sd: Mapping[str, Any], key: str) -> dict:
    out = {"kernel": conv_kernel(sd[f"{key}.weight"])}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _attn_proj(sd: Mapping[str, Any], key: str) -> dict:
    """attn_1 q/k/v/proj_out: 1×1 conv (ldm, rank-4) or linear (diffusers-style
    rank-2). The module is a 1×1 Conv either way."""
    w = to_numpy(sd[f"{key}.weight"])
    if w.ndim == 4:
        kernel = conv_kernel(w)
    else:
        kernel = w.T[None, None]  # (out,in) -> (1,1,in,out)
    out = {"kernel": kernel}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _norm(sd: Mapping[str, Any], key: str) -> dict:
    return {"scale": to_numpy(sd[f"{key}.weight"]), "bias": to_numpy(sd[f"{key}.bias"])}


def _res_block(sd: Mapping[str, Any], t: str) -> dict:
    p = {
        "norm1": _norm(sd, f"{t}.norm1"),
        "conv1": _conv(sd, f"{t}.conv1"),
        "norm2": _norm(sd, f"{t}.norm2"),
        "conv2": _conv(sd, f"{t}.conv2"),
    }
    if f"{t}.nin_shortcut.weight" in sd:
        p["nin_shortcut"] = _conv(sd, f"{t}.nin_shortcut")
    return p


def _attn_block(sd: Mapping[str, Any], t: str) -> dict:
    return {
        "norm": _norm(sd, f"{t}.norm"),
        "q": _attn_proj(sd, f"{t}.q"),
        "k": _attn_proj(sd, f"{t}.k"),
        "v": _attn_proj(sd, f"{t}.v"),
        "proj_out": _attn_proj(sd, f"{t}.proj_out"),
    }


def strip_vae_prefix(state_dict: Mapping[str, Any]) -> dict:
    """Select the VAE subtree of a combined checkpoint. Recognizes the ComfyUI/ldm
    ``first_stage_model.`` and diffusers-export ``vae.`` prefixes; a state dict that
    already starts at ``encoder./decoder.`` passes through unchanged."""
    for prefix in ("first_stage_model.", "vae."):
        sub = {
            k[len(prefix) :]: v for k, v in state_dict.items() if k.startswith(prefix)
        }
        if any(k.startswith("decoder.") for k in sub):
            return sub
    return dict(state_dict)


class _ConsumedRecorder(dict):
    """Dict view that records which keys the conversion actually read — the complete
    unconsumed-weights check (a kl-f16-style layout with in-range
    ``encoder.down.{l}.attn.{i}.*`` keys must fail loudly, not drop weights)."""

    def __init__(self, base: Mapping[str, Any]):
        super().__init__(base)
        self.used: set[str] = set()

    def __getitem__(self, key):
        self.used.add(key)
        return super().__getitem__(key)


def convert_vae_checkpoint(state_dict: Mapping[str, Any], cfg: VAEConfig) -> dict:
    """ldm-layout AutoencoderKL state dict → the param pytree of
    ``models.vae.AutoencoderKL`` (pass to ``build_vae(cfg, params=...)``)."""
    sd = _ConsumedRecorder(strip_vae_prefix(state_dict))
    n_levels = len(cfg.channel_mult)

    enc: dict[str, Any] = {
        "conv_in": _conv(sd, "encoder.conv_in"),
        "mid_block_1": _res_block(sd, "encoder.mid.block_1"),
        "mid_attn_1": _attn_block(sd, "encoder.mid.attn_1"),
        "mid_block_2": _res_block(sd, "encoder.mid.block_2"),
        "norm_out": _norm(sd, "encoder.norm_out"),
        "conv_out": _conv(sd, "encoder.conv_out"),
    }
    for level in range(n_levels):
        for i in range(cfg.num_res_blocks):
            enc[f"down_{level}_block_{i}"] = _res_block(
                sd, f"encoder.down.{level}.block.{i}"
            )
        if level != n_levels - 1:
            enc[f"down_{level}_downsample"] = {
                "conv": _conv(sd, f"encoder.down.{level}.downsample.conv")
            }

    dec: dict[str, Any] = {
        "conv_in": _conv(sd, "decoder.conv_in"),
        "mid_block_1": _res_block(sd, "decoder.mid.block_1"),
        "mid_attn_1": _attn_block(sd, "decoder.mid.attn_1"),
        "mid_block_2": _res_block(sd, "decoder.mid.block_2"),
        "norm_out": _norm(sd, "decoder.norm_out"),
        "conv_out": _conv(sd, "decoder.conv_out"),
    }
    for level in range(n_levels):
        for i in range(cfg.num_res_blocks + 1):
            dec[f"up_{level}_block_{i}"] = _res_block(
                sd, f"decoder.up.{level}.block.{i}"
            )
        if level != 0:
            dec[f"up_{level}_upsample"] = {
                "conv": _conv(sd, f"decoder.up.{level}.upsample.conv")
            }

    p: dict[str, Any] = {"encoder": enc, "decoder": dec}
    if cfg.use_quant_conv:
        p["quant_conv"] = _conv(sd, "quant_conv")
        p["post_quant_conv"] = _conv(sd, "post_quant_conv")
    # Any VAE-subtree key the walk above never read means the config doesn't match
    # the checkpoint (wrong channel_mult/num_res_blocks, attn_resolutions variant,
    # unexpected quant convs) — fail loudly instead of silently dropping weights.
    # Non-VAE siblings (loss.*, model_ema.*) are fine to ignore.
    vae_prefixes = ("encoder.", "decoder.", "quant_conv.", "post_quant_conv.")
    unused = {k for k in sd if k.startswith(vae_prefixes) and k not in sd.used}
    if unused:
        raise ValueError(
            f"{len(unused)} unconverted VAE keys (wrong cfg?): {sorted(unused)[:8]}"
        )
    return tree_to_jnp(p)
