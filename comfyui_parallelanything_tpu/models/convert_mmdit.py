"""SD3/SD3.5 MMDiT checkpoint (SAI/ComfyUI single-file layout) → models/mmdit.py.

Layout map (torch names left; optional ``model.diffusion_model.`` prefix is
stripped):

- ``x_embedder.proj``          — patch conv (dim, C, p, p) → Dense kernel
  (p·p·C, dim) in the (p_h, p_w, C) flatten order MMDiTModel.prepare emits.
- ``pos_embed``                — (1, max², dim) table → ``pos_embed/table``.
- ``t_embedder.mlp.0/.2``      → ``time_in.in/out_layer``; ``y_embedder`` →
  ``vector_in``; ``context_embedder`` → ``context_in``.
- ``joint_blocks.{i}.x_block`` → ``blocks_{i}``: ``adaLN_modulation.1`` →
  ``x_adaln/lin`` (6·dim; SAI chunk order matches), ``attn.qkv`` → fused
  DenseGeneral (dim → (3, H, 64)), ``attn.ln_q/ln_k`` (3.5 q/k RMS) →
  ``x_attn_in/ln_q|ln_k``, ``attn.proj`` → ``x_attn_proj``, ``mlp.fc1/fc2`` →
  ``x_mlp_in/out``. ``context_block`` → the ``ctx_*`` twins; the FINAL block's
  context side is pre-only (2·dim adaLN, qkv only — no proj/mlp), mirroring
  JointBlock(pre_only=True).
- ``final_layer.adaLN_modulation.1`` → ``final_mod``; ``final_layer.linear`` →
  ``final_proj``.
- SD3.5-medium (mmdit-x) dual attention: ``joint_blocks.{i}.x_block.attn2`` →
  ``x_attn_in2`` (qkv + ln_q/ln_k) and ``attn2.proj`` → ``x_attn2_proj``. The
  set of dual-attention layers and the presence of q/k RMS norms are inferred
  from the state dict and MUST match the config — a mismatch raises rather than
  silently dropping weights (``load_mmdit_checkpoint`` auto-aligns both).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

from .convert import linear_kernel, to_numpy, tree_to_jnp
from .mmdit import MMDiTConfig


def _dense(sd: Mapping[str, Any], key: str) -> dict:
    out = {"kernel": linear_kernel(sd[f"{key}.weight"])}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _qkv(sd: Mapping[str, Any], key: str, cfg: MMDiTConfig) -> dict:
    H, D = cfg.num_heads, cfg.head_dim
    w = to_numpy(sd[f"{key}.weight"])  # (3·dim, dim), rows [q; k; v]
    kernel = w.T.reshape(cfg.hidden_size, 3, H, D)
    out = {"kernel": kernel}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"]).reshape(3, H, D)
    return out


def _attn_in(sd: Mapping[str, Any], key: str, cfg: MMDiTConfig) -> dict:
    out = {"qkv": _qkv(sd, f"{key}.qkv", cfg)}
    if cfg.qk_norm:
        out["ln_q"] = to_numpy(sd[f"{key}.ln_q.weight"])
        out["ln_k"] = to_numpy(sd[f"{key}.ln_k.weight"])
    return out


def strip_mmdit_prefix(sd: Mapping[str, Any]) -> dict:
    for prefix in ("model.diffusion_model.", "diffusion_model."):
        stripped = {
            k[len(prefix):]: v for k, v in sd.items() if k.startswith(prefix)
        }
        if any(k.startswith("joint_blocks.") for k in stripped):
            return stripped
    return dict(sd)


def convert_mmdit_checkpoint(
    state_dict: Mapping[str, Any], cfg: MMDiTConfig
) -> dict:
    """SAI/ComfyUI MMDiT state dict → the ``MMDiTModel`` param pytree (pass to
    ``build_mmdit(cfg, params=...)``)."""
    sd = strip_mmdit_prefix(state_dict)
    # SD3.5-medium (mmdit-x) dual attention: which blocks carry attn2 is a fact
    # of the checkpoint — infer it and demand the config agree, so a silently
    # wrong config cannot drop weights.
    attn2_layers = tuple(sorted(
        int(k.split(".")[1])
        for k in sd
        if k.startswith("joint_blocks.") and k.endswith(".x_block.attn2.qkv.weight")
    ))
    if attn2_layers != tuple(cfg.x_block_self_attn_layers):
        raise ValueError(
            f"checkpoint has dual-attention (attn2) blocks at layers "
            f"{list(attn2_layers)} but cfg.x_block_self_attn_layers is "
            f"{list(cfg.x_block_self_attn_layers)} — build the config with "
            "x_block_self_attn_layers matching the checkpoint "
            "(sd35_medium_config for the published SD3.5-medium)"
        )
    # Same strictness for q/k RMS norm: a qk_norm=False config would silently
    # drop every ln_q/ln_k weight an SD3.5 checkpoint carries.
    has_qk_norm = any(
        k.startswith("joint_blocks.") and k.endswith(".attn.ln_q.weight") for k in sd
    )
    if has_qk_norm != cfg.qk_norm:
        raise ValueError(
            f"checkpoint {'has' if has_qk_norm else 'lacks'} q/k RMS-norm weights "
            f"(attn.ln_q/ln_k) but cfg.qk_norm is {cfg.qk_norm} — use the SD3.5 "
            "configs for SD3.5 checkpoints"
        )

    w = to_numpy(sd["x_embedder.proj.weight"])  # (dim, C, p, p)
    x_in_kernel = w.transpose(2, 3, 1, 0).reshape(-1, w.shape[0])
    p: dict[str, Any] = {
        "x_in": {
            "kernel": x_in_kernel,
            "bias": to_numpy(sd["x_embedder.proj.bias"]),
        },
        "pos_embed": {
            "table": to_numpy(sd["pos_embed"]).reshape(-1, cfg.hidden_size)
        },
        "context_in": _dense(sd, "context_embedder"),
        "time_in": {
            "in_layer": _dense(sd, "t_embedder.mlp.0"),
            "out_layer": _dense(sd, "t_embedder.mlp.2"),
        },
        "vector_in": {
            "in_layer": _dense(sd, "y_embedder.mlp.0"),
            "out_layer": _dense(sd, "y_embedder.mlp.2"),
        },
        "final_mod": _dense(sd, "final_layer.adaLN_modulation.1"),
        "final_proj": _dense(sd, "final_layer.linear"),
    }
    for i in range(cfg.depth):
        xb = f"joint_blocks.{i}.x_block"
        cb = f"joint_blocks.{i}.context_block"
        blk: dict[str, Any] = {
            "x_adaln": {"lin": _dense(sd, f"{xb}.adaLN_modulation.1")},
            "x_attn_in": _attn_in(sd, f"{xb}.attn", cfg),
            "x_attn_proj": _dense(sd, f"{xb}.attn.proj"),
            "x_mlp_in": _dense(sd, f"{xb}.mlp.fc1"),
            "x_mlp_out": _dense(sd, f"{xb}.mlp.fc2"),
            "ctx_adaln": {"lin": _dense(sd, f"{cb}.adaLN_modulation.1")},
            "ctx_attn_in": _attn_in(sd, f"{cb}.attn", cfg),
        }
        if i in attn2_layers:
            blk["x_attn_in2"] = _attn_in(sd, f"{xb}.attn2", cfg)
            blk["x_attn2_proj"] = _dense(sd, f"{xb}.attn2.proj")
        if i != cfg.depth - 1:  # pre-only final context block has no out path
            blk["ctx_attn_proj"] = _dense(sd, f"{cb}.attn.proj")
            blk["ctx_mlp_in"] = _dense(sd, f"{cb}.mlp.fc1")
            blk["ctx_mlp_out"] = _dense(sd, f"{cb}.mlp.fc2")
        p[f"blocks_{i}"] = blk
    return tree_to_jnp(p)
