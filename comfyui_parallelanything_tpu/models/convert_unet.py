"""SD-family (ldm/ComfyUI layout) UNet checkpoint → models/unet.py param tree.

Covers SD1.5 and SDXL diffusion-model state dicts (the ``model.diffusion_model.*``
subtree of a ComfyUI checkpoint — the bare UNet the reference unwraps at
any_device_parallel.py:921-930). Same conversion conventions as convert.py (fp8/bf16
upcast to f32, torch→flax layout transforms); LoRA bakes via ``bake_lora`` before
calling this.

ldm → here structural map (see models/unet.py for the module definitions):

- ``time_embed.0/.2``            → ``time_embed_0`` / ``time_embed_2``
- ``label_emb.0.0/.0.2``         → ``label_embed_0`` / ``label_embed_2`` (SDXL)
- ``input_blocks.0.0``           → ``input_conv``
- ``input_blocks.N.0`` ResBlock  → ``in_{level}_{i}_res``; ``...N.1`` transformer →
  ``in_{level}_{i}_attn``; downsample blocks → ``down_{level}``
- ``middle_block.0/1/2``         → ``mid_res1`` / ``mid_attn`` / ``mid_res2``
- ``output_blocks.N.0/.1``       → ``out_{level}_{i}_res`` / ``..._attn``; trailing
  upsample submodule → ``up_{level}``
- ``out.0/out.2``                → ``out_norm`` / ``out_conv``

ResBlock internals (creation order in UNet2D gives the flax auto-names):
``in_layers.0``→GroupNorm_0, ``in_layers.2``→Conv_0, ``emb_layers.1``→Dense_0,
``out_layers.0``→GroupNorm_1, ``out_layers.3``→Conv_1, ``skip_connection``→Conv_2.
Transformer block: ``attn{1,2}.to_{q,k,v}``→DenseGeneral (C → H×D), ``to_out.0``→
o-proj (H×D → C), ``norm{1,2,3}``→LayerNorm_{0,1,2}, GEGLU ``ff.net.0.proj``→ff_in
(x·gelu(gate), same chunk order), ``ff.net.2``→ff_out. ``proj_in``/``proj_out`` are
1×1 convs in SD1.5 and linears in SDXL — disambiguated by weight rank.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np

from .convert import conv_kernel, dense_params, to_numpy, tree_to_jnp
from .unet import UNetConfig, _heads_for, middle_depth


def _conv(sd: Mapping[str, Any], key: str) -> dict:
    out = {"kernel": conv_kernel(sd[f"{key}.weight"])}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _norm(sd: Mapping[str, Any], key: str) -> dict:
    return {
        "scale": to_numpy(sd[f"{key}.weight"]),
        "bias": to_numpy(sd[f"{key}.bias"]),
    }


def _proj_1x1(sd: Mapping[str, Any], key: str) -> dict:
    """proj_in/proj_out: conv1x1 (SD1.5, rank-4 weight) or linear (SDXL, rank-2).
    Our module is a 1×1 Conv either way, so linear weights gain the two spatial dims."""
    w = to_numpy(sd[f"{key}.weight"])
    if w.ndim == 4:
        kernel = conv_kernel(w)
    else:
        kernel = w.T[None, None, :, :]  # (in, out) → (1, 1, in, out)
    out = {"kernel": kernel}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _attn_general(w: Any, heads: int, head_dim: int) -> np.ndarray:
    """to_q/k/v (H·D, C) → DenseGeneral kernel (C, H, D)."""
    arr = to_numpy(w)
    return arr.reshape(heads, head_dim, arr.shape[1]).transpose(2, 0, 1)


def _attn_out(w: Any, heads: int, head_dim: int) -> np.ndarray:
    """to_out.0 (C, H·D) → o-proj kernel (H, D, C)."""
    arr = to_numpy(w)
    return arr.T.reshape(heads, head_dim, arr.shape[0])


def _res_block(sd: Mapping[str, Any], prefix: str, has_skip: bool) -> dict:
    blk = {
        "GroupNorm_0": _norm(sd, f"{prefix}.in_layers.0"),
        "Conv_0": _conv(sd, f"{prefix}.in_layers.2"),
        "Dense_0": dense_params(sd, f"{prefix}.emb_layers.1"),
        "GroupNorm_1": _norm(sd, f"{prefix}.out_layers.0"),
        "Conv_1": _conv(sd, f"{prefix}.out_layers.3"),
    }
    if has_skip:
        blk["Conv_2"] = _conv(sd, f"{prefix}.skip_connection")
    return blk


def _transformer_block(
    sd: Mapping[str, Any], prefix: str, heads: int, head_dim: int
) -> dict:
    def mha(name):
        out = {
            f"{name}_q": {
                "kernel": _attn_general(sd[f"{prefix}.{name}.to_q.weight"], heads, head_dim)
            },
            f"{name}_k": {
                "kernel": _attn_general(sd[f"{prefix}.{name}.to_k.weight"], heads, head_dim)
            },
            f"{name}_v": {
                "kernel": _attn_general(sd[f"{prefix}.{name}.to_v.weight"], heads, head_dim)
            },
            f"{name}_o": {
                "kernel": _attn_out(sd[f"{prefix}.{name}.to_out.0.weight"], heads, head_dim),
                "bias": to_numpy(sd[f"{prefix}.{name}.to_out.0.bias"]),
            },
        }
        return out

    blk = {
        "LayerNorm_0": _norm(sd, f"{prefix}.norm1"),
        "LayerNorm_1": _norm(sd, f"{prefix}.norm2"),
        "LayerNorm_2": _norm(sd, f"{prefix}.norm3"),
        "ff_in": dense_params(sd, f"{prefix}.ff.net.0.proj"),
        "ff_out": dense_params(sd, f"{prefix}.ff.net.2"),
    }
    blk.update(mha("attn1"))
    blk.update(mha("attn2"))
    return blk


def _spatial_transformer(
    sd: Mapping[str, Any], prefix: str, depth: int, heads: int, head_dim: int
) -> dict:
    st = {
        "GroupNorm_0": _norm(sd, f"{prefix}.norm"),
        "proj_in": _proj_1x1(sd, f"{prefix}.proj_in"),
        "proj_out": _proj_1x1(sd, f"{prefix}.proj_out"),
    }
    for d in range(depth):
        st[f"block_{d}"] = _transformer_block(
            sd, f"{prefix}.transformer_blocks.{d}", heads, head_dim
        )
    return st


def _encoder_params(sd: Mapping[str, Any], cfg: UNetConfig) -> dict:
    """The shared trunk conversion — time/label embeds, input path, middle —
    used by both the full UNet and the ControlNet (whose encoder is a copy of
    the UNet's with identical ldm naming)."""
    ch = cfg.model_channels
    p: dict[str, Any] = {}

    p["time_embed_0"] = dense_params(sd, "time_embed.0")
    p["time_embed_2"] = dense_params(sd, "time_embed.2")
    if cfg.adm_in_channels is not None:
        p["label_embed_0"] = dense_params(sd, "label_emb.0.0")
        p["label_embed_2"] = dense_params(sd, "label_emb.0.2")
    p["input_conv"] = _conv(sd, "input_blocks.0.0")

    def attn_at(level: int) -> bool:
        return level in cfg.attention_levels and cfg.transformer_depth[level] > 0

    # -- input (down) path --------------------------------------------------------
    idx = 1
    in_ch = ch
    for level, mult in enumerate(cfg.channel_mult):
        out_ch = ch * mult
        heads = _heads_for(cfg, out_ch)
        for i in range(cfg.num_res_blocks):
            p[f"in_{level}_{i}_res"] = _res_block(
                sd, f"input_blocks.{idx}.0", has_skip=(in_ch != out_ch)
            )
            if attn_at(level):
                p[f"in_{level}_{i}_attn"] = _spatial_transformer(
                    sd, f"input_blocks.{idx}.1",
                    cfg.transformer_depth[level], heads, out_ch // heads,
                )
            in_ch = out_ch
            idx += 1
        if level != len(cfg.channel_mult) - 1:
            p[f"down_{level}"] = {"Conv_0": _conv(sd, f"input_blocks.{idx}.0.op")}
            idx += 1

    # -- middle -------------------------------------------------------------------
    mid_ch = ch * cfg.channel_mult[-1]
    heads = _heads_for(cfg, mid_ch)
    p["mid_res1"] = _res_block(sd, "middle_block.0", has_skip=False)
    # Gate must mirror UNet2D exactly — the shared middle_depth() derivation
    # (incl. the refiner's transformer_depth_middle override).
    mid_depth = middle_depth(cfg)
    if mid_depth > 0:
        p["mid_attn"] = _spatial_transformer(
            sd, "middle_block.1", mid_depth, heads, mid_ch // heads
        )
        p["mid_res2"] = _res_block(sd, "middle_block.2", has_skip=False)
    else:
        p["mid_res2"] = _res_block(sd, "middle_block.1", has_skip=False)
    return p


def convert_sd_unet_checkpoint(
    state_dict: Mapping[str, Any], cfg: UNetConfig
) -> dict:
    """ldm-layout UNet state dict → ``models.unet.UNet2D`` param pytree.

    ``state_dict`` keys are relative to the UNet root (strip any
    ``model.diffusion_model.`` prefix first — see ``strip_prefix``).
    """
    sd = state_dict
    ch = cfg.model_channels
    p = _encoder_params(sd, cfg)

    def attn_at(level: int) -> bool:
        return level in cfg.attention_levels and cfg.transformer_depth[level] > 0

    # -- output (up) path ---------------------------------------------------------
    idx = 0
    for level in reversed(range(len(cfg.channel_mult))):
        out_ch = ch * cfg.channel_mult[level]
        heads = _heads_for(cfg, out_ch)
        for i in range(cfg.num_res_blocks + 1):
            # Every output res block concatenates a skip, so its input channel count
            # differs from out_ch → skip_connection always present.
            p[f"out_{level}_{i}_res"] = _res_block(
                sd, f"output_blocks.{idx}.0", has_skip=True
            )
            sub = 1
            if attn_at(level):
                p[f"out_{level}_{i}_attn"] = _spatial_transformer(
                    sd, f"output_blocks.{idx}.{sub}",
                    cfg.transformer_depth[level], heads, out_ch // heads,
                )
                sub += 1
            if i == cfg.num_res_blocks and level != 0:
                p[f"up_{level}"] = {
                    "Conv_0": _conv(sd, f"output_blocks.{idx}.{sub}.conv")
                }
            idx += 1

    p["out_norm"] = _norm(sd, "out.0")
    p["out_conv"] = _conv(sd, "out.2")
    return tree_to_jnp(p)


def strip_prefix(state_dict: Mapping[str, Any], prefix: str = "model.diffusion_model.") -> dict:
    """Select + strip a subtree prefix (ComfyUI full checkpoints carry the UNet under
    ``model.diffusion_model.``)."""
    out = {k[len(prefix):]: v for k, v in state_dict.items() if k.startswith(prefix)}
    return out if out else dict(state_dict)




# diffusers ResnetBlock2D → ldm ResBlock param-name map (suffix rewrite).
_DIFFUSERS_RES = {
    "norm1": "in_layers.0",
    "conv1": "in_layers.2",
    "time_emb_proj": "emb_layers.1",
    "norm2": "out_layers.0",
    "conv2": "out_layers.3",
    "conv_shortcut": "skip_connection",
}


def diffusers_controlnet_to_ldm(state_dict: Mapping[str, Any]) -> dict:
    """diffusers ``ControlNetModel`` key layout → ldm/cldm key layout.

    Most public SDXL ControlNets (and many SD1.5 re-releases) ship in the
    diffusers layout (``down_blocks.*``, ``controlnet_cond_embedding.*``,
    ``controlnet_down_blocks.*``); the host the reference rides on detects and
    remaps it inside its controlnet loader (the reference itself wraps
    whatever MODEL results — its unwrap at any_device_parallel.py:921-930 is
    layout-agnostic), so exported workflows load such files through the plain
    ``ControlNetLoader``. This is that remap, as a pure key rewrite —
    the tensors themselves then flow through ``convert_controlnet_checkpoint``
    unchanged (transformer/resnet internals share names between the layouts
    modulo the container renames below).

    Structure is derived from the key set itself (res-blocks per level from
    the max ``resnets.{r}`` index), so the remap needs no config:

    - ``time_embedding.linear_{1,2}``    → ``time_embed.{0,2}``
    - ``add_embedding.linear_{1,2}``     → ``label_emb.0.{0,2}`` (SDXL)
    - ``conv_in``                        → ``input_blocks.0.0``
    - ``controlnet_cond_embedding.conv_in/blocks.{0..5}/conv_out``
                                         → ``input_hint_block.{0,2..12,14}``
    - ``down_blocks.b.resnets.r``        → ``input_blocks.{1+b*(R+1)+r}.0``
      (ResnetBlock2D param names per ``_DIFFUSERS_RES``)
    - ``down_blocks.b.attentions.r``     → ``input_blocks.{1+b*(R+1)+r}.1``
    - ``down_blocks.b.downsamplers.0.conv`` → ``input_blocks.{(b+1)*(R+1)}.0.op``
    - ``mid_block.resnets.0/attentions.0/resnets.1`` → ``middle_block.0/1/2``
    - ``controlnet_down_blocks.k``       → ``zero_convs.k.0``
    - ``controlnet_mid_block``           → ``middle_block_out.0``
    """
    sd = dict(state_dict)
    res_idx = [
        (int(parts[1]), int(parts[3]))
        for parts in (k.split(".") for k in sd)
        if parts[0] == "down_blocks" and parts[2] == "resnets"
    ]
    if not res_idx:
        raise ValueError(
            "not a diffusers ControlNet state dict (no down_blocks.*.resnets)"
        )
    n_res = max(r for _, r in res_idx) + 1

    def _res_suffix(suffix: str) -> str:
        name, rest = suffix.split(".", 1)
        return f"{_DIFFUSERS_RES[name]}.{rest}"

    out: dict[str, Any] = {}
    for k, v in sd.items():
        parts = k.split(".")
        if parts[0] in ("time_embedding", "add_embedding"):
            if parts[1] not in ("linear_1", "linear_2"):
                # e.g. time_embedding.cond_proj (LCM-derived nets): aliasing
                # it onto linear_2's slot would silently corrupt weights.
                raise KeyError(f"unrecognized diffusers controlnet key: {k}")
            slot = 0 if parts[1] == "linear_1" else 2
            root = "time_embed" if parts[0] == "time_embedding" else "label_emb.0"
            nk = f"{root}.{slot}.{parts[-1]}"
        elif parts[0] == "conv_in":
            nk = f"input_blocks.0.0.{parts[-1]}"
        elif parts[0] == "controlnet_cond_embedding":
            if parts[1] == "conv_in":
                hint = 0
            elif parts[1] == "conv_out":
                hint = 14
            else:
                hint = 2 * int(parts[2]) + 2
            nk = f"input_hint_block.{hint}.{parts[-1]}"
        elif parts[0] == "down_blocks":
            b = int(parts[1])
            if parts[2] == "resnets":
                idx = 1 + b * (n_res + 1) + int(parts[3])
                nk = f"input_blocks.{idx}.0." + _res_suffix(
                    ".".join(parts[4:])
                )
            elif parts[2] == "attentions":
                idx = 1 + b * (n_res + 1) + int(parts[3])
                nk = f"input_blocks.{idx}.1." + ".".join(parts[4:])
            elif parts[2] == "downsamplers":
                idx = (b + 1) * (n_res + 1)
                nk = f"input_blocks.{idx}.0.op.{parts[-1]}"
            else:
                raise KeyError(f"unrecognized diffusers controlnet key: {k}")
        elif parts[0] == "mid_block":
            if parts[1] == "resnets":
                pos = 0 if parts[2] == "0" else 2
                nk = f"middle_block.{pos}." + _res_suffix(".".join(parts[3:]))
            elif parts[1] == "attentions":
                nk = "middle_block.1." + ".".join(parts[3:])
            else:
                raise KeyError(f"unrecognized diffusers controlnet key: {k}")
        elif parts[0] == "controlnet_down_blocks":
            nk = f"zero_convs.{parts[1]}.0.{parts[-1]}"
        elif parts[0] == "controlnet_mid_block":
            nk = f"middle_block_out.0.{parts[-1]}"
        else:
            raise KeyError(f"unrecognized diffusers controlnet key: {k}")
        out[nk] = v
    return out


def convert_controlnet_checkpoint(
    state_dict: Mapping[str, Any], cfg: UNetConfig
) -> dict:
    """ldm-layout ControlNet state dict → ``models.controlnet.ControlNet2D``
    param pytree.

    Beyond the shared encoder trunk (``_encoder_params``), the ControlNet adds:

    - ``input_hint_block.{0,2,...,14}`` → ``hint_{0..7}`` (8 convs, pixels →
      8×-reduced latent grid; the last one is a zero conv to model_channels)
    - ``zero_convs.{k}.0``              → ``zero_conv_{k}`` (one 1×1 per skip)
    - ``middle_block_out.0``            → ``mid_out``

    Keys are relative to the ControlNet root (public single-file controlnets
    ship bare; diffusers-reexports carry a ``control_model.`` prefix — strip
    it with ``strip_prefix(sd, "control_model.")`` first).
    """
    sd = state_dict
    p = _encoder_params(sd, cfg)
    for i in range(8):
        p[f"hint_{i}"] = _conv(sd, f"input_hint_block.{2 * i}")
    n_zero = 1 + sum(
        cfg.num_res_blocks + (1 if level != len(cfg.channel_mult) - 1 else 0)
        for level in range(len(cfg.channel_mult))
    )
    for k in range(n_zero):
        p[f"zero_conv_{k}"] = _conv(sd, f"zero_convs.{k}.0")
    p["mid_out"] = _conv(sd, "middle_block_out.0")
    return tree_to_jnp(p)
