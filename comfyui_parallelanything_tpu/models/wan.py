"""WAN-class video DiT — flax.linen, bf16, TPU-first. The video model family.

Capability target: the reference's README lists WAN2.2 among its tested workloads
(/root/reference/README.md:5) and its config scraper preserves video ctor kwargs —
``num_frames``, ``temporal_dim``, ``video_length`` (any_device_parallel.py:286-296).
Its pipeline mode walks a flat ``blocks``-style transformer list; this model exposes
exactly that (block list name ``blocks``, SURVEY §2b's ['...','layers'] walk).

Fresh TPU implementation of the public WAN recipe (not a port): 3D latent video
(B, T, H, W, C) patchified (1×2×2) into space-time tokens; sinusoidal timestep → MLP →
6-way adaLN modulation; N identical blocks of [modulated self-attention over all
space-time tokens with 3-axis (t, h, w) RoPE + q/k RMSNorm] → [cross-attention to text
context] → [modulated GELU FFN]; modulated head projecting back to patches. Attention
runs through the pluggable backend (ops/attention.py) — the space-time token count
(T·H·W/4) is exactly the long-sequence case sequence parallelism (parallel/sequence.py)
exists for.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.basic import modulate as _modulate, rms_normalize, timestep_embedding
from ..ops.rope import apply_rope, axis_rope_freqs
from .api import DiffusionModel, PipelineSegment, PipelineSpec


@dataclasses.dataclass(frozen=True)
class WanConfig:
    in_channels: int = 16
    out_channels: int = 16
    hidden_size: int = 1536
    ffn_dim: int = 8960
    num_heads: int = 12
    depth: int = 30
    text_dim: int = 4096       # umt5-xxl features
    freq_dim: int = 256        # sinusoidal timestep embedding width
    patch_size: tuple[int, int, int] = (1, 2, 2)  # (t, h, w)
    qk_norm_eps: float = 1e-6
    theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    # Rectified-flow velocity parameterization (see models/flux.py): routes the
    # KSampler node's k-sampler menu through flow-time sampling for WAN.
    prediction: str = "flow"
    # CLIP-vision context width (WAN2.1-style i2v checkpoints: the img_emb
    # MLP projects CLIP ViT-H penultimate states (B, 257, 1280) into extra
    # cross-attention context). None = no image branch (t2v, and WAN2.2 i2v
    # which dropped it in favor of pure channel-concat conditioning).
    img_dim: int | None = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def axes_dim(self) -> tuple[int, int, int]:
        """Per-axis RoPE dims over (t, h, w), summing to head_dim: the temporal axis
        takes the remainder after h/w get 2·(d//6) each (the public WAN split)."""
        d = self.head_dim
        hw = 2 * (d // 6)
        return (d - 2 * hw, hw, hw)


def wan_1_3b_config(**overrides) -> WanConfig:
    return dataclasses.replace(WanConfig(), **overrides)


def wan_14b_config(**overrides) -> WanConfig:
    base = WanConfig(hidden_size=5120, ffn_dim=13824, num_heads=40, depth=40)
    return dataclasses.replace(base, **overrides)


def wan_14b_i2v_config(**overrides) -> WanConfig:
    """The i2v variant: 36 in-channels = noisy latent 16 + frame mask 4 +
    encoded-image cond latent 16 (WAN2.2 channel-concat conditioning; no
    CLIP-vision branch)."""
    return wan_14b_config(in_channels=36, **overrides)


def wan_14b_i2v_clip_config(**overrides) -> WanConfig:
    """The WAN2.1-style i2v variant: channel-concat conditioning (36
    in-channels, as above) PLUS the CLIP-vision branch — ``img_emb.*``
    projects ViT-H penultimate states into 257 extra cross-attention context
    tokens served by per-block ``k_img``/``v_img`` heads. The reference's
    tested WAN set (/root/reference/README.md:5) includes these checkpoints."""
    return wan_14b_config(in_channels=36, img_dim=1280, **overrides)


class _RMSNorm(nn.Module):
    """RMSNorm in f32 with a learned scale over the last dim (WAN q/k norm runs
    over the full H·D inner dim before the head split)."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return rms_normalize(x, scale, self.eps)


class _HeadModulation(nn.Module):
    """Learned (1, 2, D) bias + time vector → head shift/scale (the public WAN
    head). A submodule (not a bare ``self.param`` in setup) so its parameter is
    initialized lazily — pipeline stages that never run the head don't need it in
    their param subtree."""

    hidden: int

    @nn.compact
    def __call__(self, vec):
        mod = self.param(
            "bias", nn.initializers.normal(0.02), (1, 2, self.hidden)
        )
        return mod + vec[:, None, :]


class WanBlock(nn.Module):
    """Modulated self-attn (3-axis RoPE) → cross-attn(text) → modulated FFN."""

    cfg: WanConfig

    @nn.compact
    def __call__(self, x, context, e, rope, context_img=None):
        """x: (B, S, D) space-time tokens; context: (B, L, D) projected text;
        e: (B, 6, D) f32 modulation chunks; rope: (cos, sin); context_img:
        optional (B, Li, D) projected CLIP-vision tokens (WAN2.1-style i2v) —
        attended by dedicated k_img/v_img heads and summed with the text
        cross-attention before the output projection (the public i2v
        cross-attn: one extra attention over image context, same queries)."""
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        # Learned per-block modulation bias added to the shared time modulation.
        mod_bias = self.param(
            "modulation", nn.initializers.normal(0.02), (1, 6, cfg.hidden_size)
        )
        e = (e + mod_bias).astype(jnp.float32)
        shift1, scale1, gate1, shift2, scale2, gate2 = (
            e[:, i][:, None, :] for i in range(6)
        )

        B, S, _ = x.shape

        # -- self-attention over all space-time tokens ----------------------------
        # q/k RMSNorm runs over the FULL inner dim (H·D) before the head split —
        # the public WAN convention (norm_q/norm_k are RMSNorm(dim)); per-head
        # norm would be numerically different and break checkpoint fidelity.
        h = _modulate(
            nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype, name="norm1")(x),
            shift1, scale1,
        )
        q = nn.Dense(H * D, dtype=cfg.dtype, name="self_q")(h)
        k = nn.Dense(H * D, dtype=cfg.dtype, name="self_k")(h)
        v = nn.Dense(H * D, dtype=cfg.dtype, name="self_v")(h)
        q = _RMSNorm(cfg.qk_norm_eps, name="self_q_norm")(q).reshape(B, S, H, D)
        k = _RMSNorm(cfg.qk_norm_eps, name="self_k_norm")(k).reshape(B, S, H, D)
        v = v.reshape(B, S, H, D)
        cos, sin = rope
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attention(q, k, v).reshape(B, S, -1)
        attn = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="self_o")(attn)
        x = x + gate1.astype(cfg.dtype) * attn

        # -- cross-attention to text (no rope, no gate; affine pre-norm) ----------
        L = context.shape[1]
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm3")(x)
        q = nn.Dense(H * D, dtype=cfg.dtype, name="cross_q")(h)
        k = nn.Dense(H * D, dtype=cfg.dtype, name="cross_k")(context)
        v = nn.Dense(H * D, dtype=cfg.dtype, name="cross_v")(context)
        q = _RMSNorm(cfg.qk_norm_eps, name="cross_q_norm")(q).reshape(B, S, H, D)
        k = _RMSNorm(cfg.qk_norm_eps, name="cross_k_norm")(k).reshape(B, L, H, D)
        v = v.reshape(B, L, H, D)
        attn = attention(q, k, v)
        if context_img is not None:
            Li = context_img.shape[1]
            k_i = nn.Dense(H * D, dtype=cfg.dtype, name="cross_k_img")(context_img)
            v_i = nn.Dense(H * D, dtype=cfg.dtype, name="cross_v_img")(context_img)
            k_i = _RMSNorm(cfg.qk_norm_eps, name="cross_k_img_norm")(k_i)
            attn = attn + attention(
                q, k_i.reshape(B, Li, H, D), v_i.reshape(B, Li, H, D)
            )
        attn = attn.reshape(B, S, -1)
        x = x + nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="cross_o")(attn)

        # -- FFN -------------------------------------------------------------------
        h = _modulate(
            nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype, name="norm2")(x),
            shift2, scale2,
        )
        h = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype, name="ffn_in")(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="ffn_out")(nn.gelu(h))
        return x + gate2.astype(cfg.dtype) * h


class WanModel(nn.Module):
    """forward(x video latent (B, T, H, W, C), timesteps (B,), context (B, L, text_dim)).

    Setup-style for the staged pipeline decomposition (same protocol as FluxModel):
    carry = {x, context, e, vec, rope_cos, rope_sin}.
    """

    cfg: WanConfig

    def setup(self):
        cfg = self.cfg
        self.patch_embedding = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.text_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.text_hidden = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.time_in = nn.Dense(cfg.hidden_size, dtype=jnp.float32)
        self.time_hidden = nn.Dense(cfg.hidden_size, dtype=jnp.float32)
        self.time_projection = nn.Dense(6 * cfg.hidden_size, dtype=jnp.float32)
        self.blocks = [WanBlock(cfg) for _ in range(cfg.depth)]
        if cfg.img_dim is not None:
            # The public i2v img_emb MLPProj: LN(img_dim) → Dense → GELU →
            # Dense → LN(hidden), projecting CLIP-vision penultimate states
            # into extra cross-attention context tokens.
            self.img_ln_in = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)
            self.img_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
            self.img_hidden = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
            self.img_ln_out = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32)
        # Head modulation is a learned (1, 2, D) bias added to the time vector —
        # the public WAN head (head.modulation + e), NOT a projection.
        self.head_modulation = _HeadModulation(cfg.hidden_size)
        self.head_norm = nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype)
        pt, ph, pw = cfg.patch_size
        self.head_proj = nn.Dense(pt * ph * pw * cfg.out_channels, dtype=jnp.float32)

    def prepare(self, x, timesteps, context=None, clip_fea=None, **kwargs):
        cfg = self.cfg
        B, T, Hh, Ww, C = x.shape
        pt, ph, pw = cfg.patch_size
        tp, hp, wp = T // pt, Hh // ph, Ww // pw

        # (1, 2, 2) patchify → (B, tp·hp·wp, pt·ph·pw·C)
        tok = x.astype(cfg.dtype).reshape(B, tp, pt, hp, ph, wp, pw, C)
        tok = tok.transpose(0, 1, 3, 5, 2, 4, 6, 7).reshape(
            B, tp * hp * wp, pt * ph * pw * C
        )
        tok = self.patch_embedding(tok)

        if context is None:
            raise ValueError("WAN requires text context tokens")
        ctx = self.text_hidden(
            nn.gelu(self.text_in(context.astype(cfg.dtype)))
        )

        vec = self.time_hidden(
            nn.silu(
                self.time_in(
                    timestep_embedding(timesteps, cfg.freq_dim, time_factor=1000.0)
                )
            )
        )
        e = self.time_projection(nn.silu(vec)).reshape(B, 6, cfg.hidden_size)
        vec = vec.astype(jnp.float32)  # carried for the head modulation

        # 3-axis (t, h, w) position ids for RoPE.
        tt = jnp.arange(tp, dtype=jnp.int32)
        hh = jnp.arange(hp, dtype=jnp.int32)
        ww = jnp.arange(wp, dtype=jnp.int32)
        grid = jnp.stack(
            jnp.meshgrid(tt, hh, ww, indexing="ij"), axis=-1
        ).reshape(1, tp * hp * wp, 3)
        ids = jnp.broadcast_to(grid, (B, tp * hp * wp, 3))
        cos, sin = axis_rope_freqs(ids, self.cfg.axes_dim, cfg.theta)
        carry = {
            "x": tok, "context": ctx, "e": e, "vec": vec,
            "rope_cos": cos, "rope_sin": sin,
        }
        if clip_fea is not None:
            if cfg.img_dim is None:
                raise ValueError(
                    "clip_fea passed but this WAN config has no CLIP-vision "
                    "branch (img_dim=None) — load a WAN2.1-style i2v "
                    "checkpoint (wan_14b_i2v_clip_config)"
                )
            ci = self.img_ln_in(clip_fea.astype(jnp.float32))
            ci = self.img_hidden(nn.gelu(self.img_in(ci.astype(cfg.dtype))))
            carry["context_img"] = self.img_ln_out(ci).astype(cfg.dtype)
        return carry

    def block_step(self, carry, i: int):
        x = self.blocks[i](
            carry["x"], carry["context"], carry["e"],
            (carry["rope_cos"], carry["rope_sin"]),
            context_img=carry.get("context_img"),
        )
        return {**carry, "x": x}

    def finalize(self, carry, out_shape: tuple[int, ...]):
        cfg = self.cfg
        B, T, Hh, Ww, _ = out_shape
        pt, ph, pw = cfg.patch_size
        tp, hp, wp = T // pt, Hh // ph, Ww // pw
        x, vec = carry["x"], carry["vec"]
        mod = self.head_modulation(vec)
        shift, scale = mod[:, 0][:, None, :], mod[:, 1][:, None, :]
        x = _modulate(self.head_norm(x), shift, scale)
        x = self.head_proj(x.astype(jnp.float32))
        x = x.reshape(B, tp, hp, wp, pt, ph, pw, cfg.out_channels)
        x = x.transpose(0, 1, 4, 2, 5, 3, 6, 7)
        return x.reshape(B, T, Hh, Ww, cfg.out_channels)

    def __call__(self, x, timesteps, context=None, clip_fea=None, **kwargs):
        carry = self.prepare(x, timesteps, context, clip_fea=clip_fea)
        for i in range(self.cfg.depth):
            carry = self.block_step(carry, i)
        return self.finalize(carry, x.shape)


def _wan_pipeline_spec(module: WanModel, cfg: WanConfig) -> PipelineSpec:
    def prepare(params, x, t, context=None, clip_fea=None, **kw):
        return module.apply(
            {"params": params}, x, t, context, clip_fea=clip_fea,
            method=WanModel.prepare,
        )

    def make_block(i):
        def fn(params, carry):
            return module.apply({"params": params}, carry, i, method=WanModel.block_step)

        return fn

    def finalize(params, carry, out_shape):
        return module.apply(
            {"params": params}, carry, out_shape, method=WanModel.finalize
        )

    prepare_keys = (
        "patch_embedding", "text_in", "text_hidden",
        "time_in", "time_hidden", "time_projection",
    )
    if cfg.img_dim is not None:
        prepare_keys += ("img_ln_in", "img_in", "img_hidden", "img_ln_out")
    return PipelineSpec(
        prepare_keys=prepare_keys,
        prepare=prepare,
        segments=tuple(
            PipelineSegment((f"blocks_{i}",), make_block(i), f"blocks[{i}]")
            for i in range(cfg.depth)
        ),
        finalize_keys=("head_modulation", "head_proj"),
        finalize=finalize,
    )


def apply_i2v_conditioning(base: DiffusionModel, cond=None, clip_fea=None):
    """Compose WAN i2v conditioning into a DiffusionModel: every denoise
    step's input becomes ``concat([x, cond], channel)`` (``cond`` = 4-channel
    frame mask ‖ encoded start-frames latent, the WAN i2v channel-concat
    contract) and, when ``clip_fea`` is given (WAN2.1-style checkpoints with
    the img_emb branch), the CLIP-vision penultimate states ride the call as
    the ``clip_fea`` kwarg. Like ``apply_inpaint_conditioning``
    (models/unet.py), the conditioning tensors live in the merged params
    pytree so the composition places/shards through ``parallelize`` and the
    whole step stays one jit program. CFG's doubled batch (cond ‖ uncond in
    one forward) tiles both tensors. The reference's WAN i2v workloads get
    this conditioning from the host model it wraps
    (any_device_parallel.py:921-930 unwraps it; /root/reference/README.md:5
    lists WAN2.2 in the tested set).

    Config-aware (host WAN21.concat_cond semantics): on a t2v model
    (in_channels == out_channels) the channel-concat tag is IGNORED with a
    warning (stock models without extra channels never call concat_cond); on
    an i2v model with no start-image cond, the missing channels zero-fill
    (stock zero-fills concat_latent_image, so a WanImageToVideo wired with
    only clip_vision_output still samples); a cond of the wrong width raises
    at compose time instead of dying in patchify."""
    cfg = base.config
    expected = None
    in_ch = getattr(cfg, "in_channels", None)
    out_ch = getattr(cfg, "out_channels", None)
    if in_ch is not None and out_ch is not None:
        expected = in_ch - out_ch  # extra channels the checkpoint consumes
        if expected <= 0:
            if cond is not None or clip_fea is not None:
                from ..utils.logging import get_logger

                get_logger().warning(
                    "i2v conditioning on a t2v checkpoint (in_channels == "
                    f"{in_ch}, no concat slots) — ignored, sampling proceeds "
                    "unconditioned (stock concat_cond semantics)"
                )
            return base
        if cond is not None and cond.shape[-1] != expected:
            raise ValueError(
                f"i2v cond carries {cond.shape[-1]} channels but the "
                f"checkpoint concatenates {expected} "
                f"(in {in_ch} − latent {out_ch}) — the WanImageToVideo VAE "
                "does not match this model"
            )
    if clip_fea is not None and getattr(cfg, "img_dim", None) is None:
        # A WAN2.1-template graph (clip_vision_output wired) reused on a
        # checkpoint without the img_emb branch (WAN2.2 i2v, t2v): stock's
        # model simply ignores clip_fea when it has no img_emb — degrade the
        # same way instead of raising mid-sampling in WanModel.prepare.
        from ..utils.logging import get_logger

        get_logger().warning(
            "clip_vision_output on a WAN checkpoint without the CLIP-vision "
            "branch (no img_emb weights; WAN2.2-style) — image embeds "
            "ignored, channel-concat conditioning still applies"
        )
        clip_fea = None
    merged: dict = {"base": base.params}
    if cond is not None:
        merged["cond"] = jnp.asarray(cond)
    if clip_fea is not None:
        merged["clip_fea"] = jnp.asarray(clip_fea)
    base_apply = base.apply
    fill_ch = expected if cond is None else None

    def _tile_to(a, batch, ndim):
        if a.shape[0] != batch:
            if batch % a.shape[0]:
                raise ValueError(
                    f"i2v conditioning batch {a.shape[0]} does not divide "
                    f"model batch {batch}"
                )
            a = jnp.tile(
                a, (batch // a.shape[0],) + (1,) * (ndim - 1)
            )
        return a

    def apply(p, x, timesteps, context=None, **kw):
        x_in = x
        if "cond" in p:
            c = _tile_to(p["cond"], x.shape[0], x.ndim)
            x_in = jnp.concatenate([x, c.astype(x.dtype)], axis=-1)
        elif fill_ch:
            # No start-image cond on an i2v checkpoint: zero-fill the concat
            # slots (zeros frame mask = nothing given, zeros cond latent).
            x_in = jnp.concatenate(
                [x, jnp.zeros(x.shape[:-1] + (fill_ch,), x.dtype)], axis=-1
            )
        if "clip_fea" in p:
            kw = {**kw, "clip_fea": _tile_to(p["clip_fea"], x.shape[0], 3)}
        return base_apply(p["base"], x_in, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply, params=merged, name=f"{base.name}+i2v",
        config=base.config,
    )


def build_wan(
    cfg: WanConfig,
    rng=None,
    sample_shape=(1, 4, 16, 16, 16),
    txt_len=64,
    name="wan",
    params=None,
) -> DiffusionModel:
    """Build a WAN DiffusionModel; ``params`` skips initialization (load path)."""
    module = WanModel(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        ctx = jnp.zeros((sample_shape[0], txt_len, cfg.text_dim), jnp.float32)
        kwargs = {}
        if cfg.img_dim is not None:
            # 257 = CLIP ViT penultimate tokens (CLS + 16² patches); init must
            # trace the image branch so its params exist in the pytree.
            kwargs["clip_fea"] = jnp.zeros(
                (sample_shape[0], 257, cfg.img_dim), jnp.float32
            )
        params = module.init(rng, x, t, ctx, **kwargs)["params"]

    def apply(params, x, timesteps, context=None, **kw):
        return module.apply({"params": params}, x, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply,
        params=params,
        name=name,
        config=cfg,
        block_lists={"blocks": cfg.depth},
        pipeline_spec=_wan_pipeline_spec(module, cfg),
    )
