"""Weight-only int8 quantization for diffusion model pytrees.

The reference preserves fp8-*stored* checkpoints through cloning and downcasts
them per device capability (any_device_parallel.py:93-124, 688-699) — its only
compression story. Here the TPU-native equivalent is symmetric per-channel int8
weight quantization applied after load:

- each large weight leaf becomes a ``QuantTensor(q=int8, scale=f32)`` pytree
  node (per-output-channel scales: ``w ≈ q · scale``);
- ``QuantTensor`` is a registered pytree, so placement (``jax.device_put`` with
  shardings), FSDP leaf sharding, pipeline sub-pytree staging, and donation all
  treat the int8 payload like any other leaf — no special cases anywhere in the
  parallel layer;
- the model's ``apply`` dequantizes inside jit: XLA reads the int8 bytes from
  HBM (half the bf16 traffic for weight-bound regimes) and widens on-chip.

Why it matters on a v5e: a flux-dev-class bf16 replica (~24 GB) does not fit a
16 GB chip; at int8 (~12 GB) it does — so quantization turns "must shard (FSDP)"
into "may replicate", trading a bounded quantization error (per-channel symmetric
int8 on conv/dense kernels is well inside diffusion sampling tolerance) for the
all-gather traffic FSDP would pay every step.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantTensor:
    """Symmetric per-channel int8 weight: ``w ≈ q.astype(f32) * scale``.

    ``scale`` broadcasts against ``q`` (kept with a trailing axis of the same
    rank, size 1 everywhere except the channel axis)."""

    q: Any      # int8, original shape
    scale: Any  # f32, broadcastable to q's shape

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.bfloat16):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)


def _quantize_leaf(w, channel_axis: int) -> QuantTensor:
    wf = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(i for i in range(wf.ndim) if i != channel_axis)
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QuantTensor(q=q, scale=scale)


def int8_eligible(shape, min_size: int = 2**16) -> bool:
    """THE min-size/rank rule deciding which leaves quantize to int8.
    Shared by ``quantize_params``, bench's ``_synth_int8_params`` synthesis,
    and the dryrun's abstract flux_stream byte profile (§19b) — one rule,
    so the synthesized/abstract byte budgets can never drift from what
    quantization actually stores."""
    shape = tuple(shape)
    size = 1
    for s in shape:
        size *= int(s)
    return len(shape) >= 2 and size >= min_size


def synth_int8_nbytes(shapes, min_size: int = 2**16) -> int:
    """Stored bytes of an ABSTRACT pytree (ShapeDtypeStructs / shape stubs)
    under the int8 synthesis rule: eligible leaves count int8 bytes plus
    the per-output-channel f32 scale vector, the rest bf16 — sizes a
    12B-class checkpoint without materializing anything."""
    total = 0
    for leaf in jax.tree.leaves(shapes):
        shape = tuple(getattr(leaf, "shape", ()))
        size = 1
        for s in shape:
            size *= int(s)
        if int8_eligible(shape, min_size):
            total += size + int(shape[-1]) * 4  # int8 q + f32 scale row
        else:
            total += size * 2  # bf16
    return total


def quantize_params(params, min_size: int = 2**16):
    """Quantize every large ≥2-D weight leaf to per-channel int8.

    Channel axis = the last axis (flax Dense kernels are (in, out), convs
    (k..., in, out) — the output channel is last in both). Small leaves (norms,
    biases, embeddings under ``min_size``) stay in their original dtype: they
    are a rounding error of the byte budget and the most precision-sensitive.
    """

    def leaf(w):
        if isinstance(w, QuantTensor):
            return w
        shape = tuple(getattr(w, "shape", ()))
        if not int8_eligible(shape, min_size):
            return w
        return _quantize_leaf(w, channel_axis=len(shape) - 1)

    return jax.tree.map(leaf, params, is_leaf=lambda x: isinstance(x, QuantTensor))


def dequantize_params(params, dtype=jnp.bfloat16):
    """QuantTensor leaves → real arrays (inside jit: int8 HBM reads, on-chip
    widening; XLA fuses the multiply into the consumer where profitable)."""
    return jax.tree.map(
        lambda l: l.dequantize(dtype) if isinstance(l, QuantTensor) else l,
        params,
        is_leaf=lambda x: isinstance(x, QuantTensor),
    )


def param_bytes(params) -> int:
    """Total stored bytes of a (possibly quantized) pytree."""
    return sum(
        int(l.size) * l.dtype.itemsize for l in jax.tree.leaves(params)
    )


def quantize_model(model, min_size: int = 2**16, dtype=jnp.bfloat16):
    """DiffusionModel → DiffusionModel with int8-stored weights.

    The returned model's ``apply`` dequantizes inside the traced computation, so
    every downstream consumer — ``parallelize``, pipelines, samplers — works
    unchanged; only the stored bytes (and HBM weight traffic) halve."""
    import dataclasses as _dc

    base_apply = model.apply

    def apply(params, *args, **kwargs):
        return base_apply(dequantize_params(params, dtype), *args, **kwargs)

    q_params = quantize_params(model.params, min_size)

    # Pipeline staging: stage programs receive per-stage sub-pytrees and call
    # spec closures bound to the ORIGINAL module apply — rebind them through the
    # same dequantize wrapper.
    spec = model.pipeline_spec
    if spec is not None:
        def wrap_stage(fn):
            def wrapped(params, *a, **k):
                return fn(dequantize_params(params, dtype), *a, **k)
            return wrapped

        spec = _dc.replace(
            spec,
            prepare=wrap_stage(spec.prepare),
            segments=tuple(
                _dc.replace(seg, fn=wrap_stage(seg.fn)) for seg in spec.segments
            ),
            finalize=wrap_stage(spec.finalize),
        )

    return _dc.replace(model, apply=apply, params=q_params, pipeline_spec=spec)
