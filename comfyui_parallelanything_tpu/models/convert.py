"""Torch-checkpoint → JAX parameter conversion, with LoRA baking.

SURVEY §7 hard parts 2 and 5: the one place torch legitimately remains is CPU-side
checkpoint loading. The reference replicates live torch modules, preserving fp8-stored
weights and LoRA patches through cloning (any_device_parallel.py:93-124, 688-699,
971-1004). Here the equivalents are:

- fp8-on-disk weights upcast at load — v5e has no fp8 matmul path, so fp8 tensors
  become the model's compute dtype on conversion (parity: fp8→fp16 downcast on
  non-fp8 devices, 688-699);
- LoRA is baked into the base weights *before* conversion (``bake_lora``) — the
  analogue of the reference's bake-before-replicate ``patch_model(device_to=...)``
  call (992-1004): one merged weight set, replicated by sharding, no per-step patch
  math;
- name/layout mapping: torch ``Linear.weight`` is (out, in) → flax ``kernel`` is
  (in, out); torch ``Conv2d.weight`` is (O, I, kH, kW) → flax (kH, kW, I, O); fused
  qkv (3·H·D, in) → DenseGeneral kernels (in, 3, H, D).

All functions take a flat ``{name: tensor}`` state dict (torch tensors or numpy
arrays) and return JAX pytrees; no torch import is required unless torch tensors are
actually passed in.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger
from .flux import FluxConfig

_FP8_DTYPE_NAMES = (
    # Parity: is_float8_dtype's five-name string match (93-98).
    "float8_e4m3fn",
    "float8_e4m3fnuz",
    "float8_e5m2",
    "float8_e5m2fnuz",
    "float8_e8m0fnu",
)


def is_float8_dtype(dtype: Any) -> bool:
    """String-matched fp8 detection, torch- and numpy-dtype agnostic (parity 93-98)."""
    return any(name in str(dtype) for name in _FP8_DTYPE_NAMES)


def to_numpy(t: Any) -> np.ndarray:
    """Any checkpoint tensor → float32 numpy. fp8/bf16/f16 upcast to f32 here; the
    model's compute dtype policy re-casts at apply time (bf16 matmuls on TPU)."""
    if isinstance(t, np.ndarray):
        return t.astype(np.float32) if t.dtype != np.float32 else t
    # torch tensor (duck-typed so numpy-only callers never import torch)
    if hasattr(t, "detach"):
        t = t.detach()
        if is_float8_dtype(t.dtype) or str(t.dtype) in ("torch.bfloat16", "torch.float16"):
            t = t.float()
        return t.cpu().numpy().astype(np.float32)
    return np.asarray(t, dtype=np.float32)


# --------------------------------------------------------------------------------------
# Layout transforms (torch → flax)
# --------------------------------------------------------------------------------------


def linear_kernel(w: Any) -> np.ndarray:
    """(out, in) → (in, out)."""
    return to_numpy(w).T


def conv_kernel(w: Any) -> np.ndarray:
    """(O, I, kH, kW) → (kH, kW, I, O)."""
    return to_numpy(w).transpose(2, 3, 1, 0)


def qkv_kernel(w: Any, heads: int, head_dim: int) -> np.ndarray:
    """Fused qkv (3·H·D, in) → DenseGeneral kernel (in, 3, H, D)."""
    arr = to_numpy(w)
    in_dim = arr.shape[1]
    return arr.reshape(3, heads, head_dim, in_dim).transpose(3, 0, 1, 2)


def qkv_bias(b: Any, heads: int, head_dim: int) -> np.ndarray:
    """(3·H·D,) → (3, H, D)."""
    return to_numpy(b).reshape(3, heads, head_dim)


# --------------------------------------------------------------------------------------
# LoRA baking (bake-before-convert; parity: patch_model at 992-1004)
# --------------------------------------------------------------------------------------


def _lora_pairs(lora_sd: Mapping[str, Any]) -> dict[str, tuple[Any, Any, float | None]]:
    """Collect (down/A, up/B, alpha) per base key from either naming convention:
    kohya ``{base}.lora_down.weight`` / ``.lora_up.weight`` / ``.alpha`` or
    diffusers/PEFT ``{base}.lora_A.weight`` / ``.lora_B.weight``."""
    pairs: dict[str, dict[str, Any]] = {}
    for key, tensor in lora_sd.items():
        for down_tag, up_tag in ((".lora_down.weight", ".lora_up.weight"),
                                 (".lora_A.weight", ".lora_B.weight")):
            if key.endswith(down_tag):
                pairs.setdefault(key[: -len(down_tag)], {})["down"] = tensor
                break
            if key.endswith(up_tag):
                pairs.setdefault(key[: -len(up_tag)], {})["up"] = tensor
                break
        else:
            if key.endswith(".alpha"):
                pairs.setdefault(key[: -len(".alpha")], {})["alpha"] = tensor
    out = {}
    for base, parts in pairs.items():
        if "down" in parts and "up" in parts:
            alpha = parts.get("alpha")
            out[base] = (
                parts["down"],
                parts["up"],
                float(to_numpy(alpha)) if alpha is not None else None,
            )
    return out


def bake_lora(
    state_dict: Mapping[str, Any],
    lora_sd: Mapping[str, Any],
    strength: float = 1.0,
) -> dict[str, np.ndarray]:
    """Merge LoRA deltas into base weights: ``W += strength · (alpha/r) · up @ down``.

    Returns a new float32 state dict; unmatched LoRA keys are logged and skipped
    (the reference prints-and-continues on patch failures, 1002-1004). Matching is by
    base-key prefix with '.weight' appended, tolerating the common ``lora_unet_`` /
    underscore-flattened prefixes by also trying a dot-normalized form.
    """
    merged = {k: to_numpy(v) for k, v in state_dict.items()}
    by_normalized = {k.replace(".", "_"): k for k in merged}
    unmatched = []
    for base, (down, up, alpha) in _lora_pairs(lora_sd).items():
        target = None
        for cand in (f"{base}.weight", base):
            if cand in merged:
                target = cand
                break
        if target is None:
            # kohya convention flattens dots to underscores and prefixes the module
            # tree root (e.g. lora_unet_double_blocks_0_img_attn_qkv).
            stripped = base
            for prefix in ("lora_unet_", "lora_transformer_", "lora_te1_",
                           "lora_te2_", "lora_te_", "lora_"):
                if stripped.startswith(prefix):
                    stripped = stripped[len(prefix):]
                    break
            key = by_normalized.get(f"{stripped}_weight".replace(".", "_"))
            if key is None:
                key = by_normalized.get(stripped.replace(".", "_"))
            if key is None:
                # Prefixed sub-dicts (a text tower extracted as
                # ``cond_stage_model.transformer.text_model...``): the LoRA
                # base names only the module-tree suffix, so fall back to a
                # unique suffix match. Ambiguity (two towers in one dict)
                # skips — callers bake per tower with pre-filtered LoRA keys.
                want = "_" + f"{stripped}_weight".replace(".", "_")
                hits = [v for k, v in by_normalized.items() if k.endswith(want)]
                key = hits[0] if len(hits) == 1 else None
            target = key
        if target is None:
            unmatched.append(base)
            continue
        down_a, up_a = to_numpy(down), to_numpy(up)
        rank = down_a.shape[0]
        scale = strength * ((alpha / rank) if alpha is not None else 1.0)
        w = merged[target]
        if w.ndim == 4:  # conv: (O, I, kH, kW) with 1x1 or kxk lora
            delta = np.einsum(
                "or...,ri...->oi...",
                up_a.reshape(up_a.shape[0], rank, *up_a.shape[2:]),
                down_a.reshape(rank, down_a.shape[1], *down_a.shape[2:]),
            )
            if delta.shape != w.shape:  # 1x1 lora on kxk conv: broadcast at center
                unmatched.append(base)
                continue
            merged[target] = w + scale * delta
        else:
            merged[target] = w + scale * (up_a @ down_a)
    if unmatched:
        get_logger().warning(
            "bake_lora: %d LoRA key(s) had no base match and were skipped: %s",
            len(unmatched),
            unmatched[:5],
        )
    return merged


# --------------------------------------------------------------------------------------
# FLUX checkpoint map (official BFL layout → models/flux.py param tree)
# --------------------------------------------------------------------------------------


def dense_params(sd: Mapping[str, Any], key: str) -> dict:
    """torch ``{key}.weight``/``.bias`` → flax Dense ``kernel``/``bias``."""
    out = {"kernel": linear_kernel(sd[f"{key}.weight"])}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def tree_to_jnp(tree: Any) -> Any:
    """Nested dict of numpy arrays → jnp arrays (shared by all converters)."""
    if isinstance(tree, dict):
        return {k: tree_to_jnp(v) for k, v in tree.items()}
    return jnp.asarray(tree)


def _mlp_embedder(sd: Mapping[str, Any], prefix: str) -> dict:
    return {
        "in_layer": {
            "kernel": linear_kernel(sd[f"{prefix}.in_layer.weight"]),
            "bias": to_numpy(sd[f"{prefix}.in_layer.bias"]),
        },
        "out_layer": {
            "kernel": linear_kernel(sd[f"{prefix}.out_layer.weight"]),
            "bias": to_numpy(sd[f"{prefix}.out_layer.bias"]),
        },
    }


def convert_flux_checkpoint(
    state_dict: Mapping[str, Any],
    cfg: FluxConfig,
    lora_sd: Mapping[str, Any] | None = None,
    lora_strength: float = 1.0,
) -> dict:
    """Official FLUX state dict (flux1-dev/schnell layout) → the param pytree of
    ``models.flux.FluxModel``. LoRA, when given, is baked first (992-1004 parity)."""
    sd = dict(state_dict)
    if lora_sd:
        sd = bake_lora(sd, lora_sd, lora_strength)
    H, D = cfg.num_heads, cfg.head_dim
    p: dict[str, Any] = {}

    p["img_in"] = dense_params(sd, "img_in")
    p["txt_in"] = dense_params(sd, "txt_in")
    p["time_in"] = _mlp_embedder(sd, "time_in")
    p["vector_in"] = _mlp_embedder(sd, "vector_in")
    if cfg.guidance_embed:
        p["guidance_in"] = _mlp_embedder(sd, "guidance_in")

    for i in range(cfg.depth):
        t = f"double_blocks.{i}"
        blk: dict[str, Any] = {}
        for stream in ("img", "txt"):
            blk[f"{stream}_mod"] = {"lin": dense_params(sd, f"{t}.{stream}_mod.lin")}
            blk[f"{stream}_attn_qkv"] = {
                "kernel": qkv_kernel(sd[f"{t}.{stream}_attn.qkv.weight"], H, D),
                "bias": qkv_bias(sd[f"{t}.{stream}_attn.qkv.bias"], H, D),
            }
            blk[f"{stream}_attn_norm"] = {
                "query_norm": to_numpy(sd[f"{t}.{stream}_attn.norm.query_norm.scale"]),
                "key_norm": to_numpy(sd[f"{t}.{stream}_attn.norm.key_norm.scale"]),
            }
            blk[f"{stream}_attn_proj"] = dense_params(sd, f"{t}.{stream}_attn.proj")
            blk[f"{stream}_mlp_in"] = dense_params(sd, f"{t}.{stream}_mlp.0")
            blk[f"{stream}_mlp_out"] = dense_params(sd, f"{t}.{stream}_mlp.2")
        p[f"double_blocks_{i}"] = blk

    for i in range(cfg.depth_single_blocks):
        t = f"single_blocks.{i}"
        p[f"single_blocks_{i}"] = {
            "modulation": {"lin": dense_params(sd, f"{t}.modulation.lin")},
            "linear1": dense_params(sd, f"{t}.linear1"),
            "linear2": dense_params(sd, f"{t}.linear2"),
            "norm": {
                "query_norm": to_numpy(sd[f"{t}.norm.query_norm.scale"]),
                "key_norm": to_numpy(sd[f"{t}.norm.key_norm.scale"]),
            },
        }

    # final_layer.adaLN_modulation.1 emits (shift, scale); our final_mod emits the
    # same two chunks in the same order.
    p["final_mod"] = dense_params(sd, "final_layer.adaLN_modulation.1")
    p["final_proj"] = dense_params(sd, "final_layer.linear")

    return tree_to_jnp(p)
