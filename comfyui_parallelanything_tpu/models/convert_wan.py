"""WAN video-DiT checkpoint (official Wan2.x layout) → models/wan.py param tree.

The reference lists WAN2.2 among its tested workloads (/root/reference/README.md:5)
and replicates the torch module per device; here the official safetensors layout
converts once into the functional param tree. Layout map (module names on the left
are the public Wan2.x release's):

- ``patch_embedding``            — Conv3d with kernel == stride == patch_size; its
  (O, C, pt, ph, pw) weight folds into our patchify Dense by transposing to
  (pt, ph, pw, C, O) and flattening — exactly the (pt, ph, pw, C) token order
  WanModel.prepare emits.
- ``text_embedding.0/.2``        → ``text_in`` / ``text_hidden``
- ``time_embedding.0/.2``        → ``time_in`` / ``time_hidden``
- ``time_projection.1``          → ``time_projection``
- ``blocks.{i}.self_attn.{q,k,v,o}``        → ``blocks_{i}.self_{q,k,v,o}``
- ``blocks.{i}.self_attn.norm_{q,k}.weight``→ ``blocks_{i}.self_{q,k}_norm.scale``
- ``blocks.{i}.cross_attn...``              → ``blocks_{i}.cross_*`` (same pattern)
- ``blocks.{i}.norm3.{weight,bias}``        → ``blocks_{i}.norm3`` (affine pre-norm;
  norm1/norm2 are affine-free in both implementations — no weights to map)
- ``blocks.{i}.ffn.0/.2``                   → ``blocks_{i}.ffn_in`` / ``ffn_out``
- ``blocks.{i}.modulation``                 → ``blocks_{i}.modulation`` (1, 6, D)
- ``head.head``                             → ``head_proj``
- ``head.modulation``                       → ``head_modulation`` (1, 2, D)

The WAN2.1-style i2v CLIP-image branch converts when the config carries
``img_dim`` (wan_14b_i2v_clip_config):

- ``img_emb.proj.0/.1/.3/.4`` → ``img_ln_in`` / ``img_in`` / ``img_hidden`` /
  ``img_ln_out`` (the MLPProj LN→Dense→GELU→Dense→LN stack)
- ``blocks.{i}.cross_attn.{k,v}_img``   → ``blocks_{i}.cross_{k,v}_img``
- ``blocks.{i}.cross_attn.norm_k_img.weight`` → ``blocks_{i}.cross_k_img_norm``

Without ``img_dim`` those keys are ignored (a t2v config loading an i2v file);
ema/optimizer sidecars are always ignored.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

from .convert import linear_kernel, to_numpy, tree_to_jnp
from .wan import WanConfig


def _dense(sd: Mapping[str, Any], key: str, bias: bool = True) -> dict:
    out = {"kernel": linear_kernel(sd[f"{key}.weight"])}
    if bias and f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _rms(sd: Mapping[str, Any], key: str) -> dict:
    return {"scale": to_numpy(sd[f"{key}.weight"])}


def _ln(sd: Mapping[str, Any], key: str) -> dict:
    return {"scale": to_numpy(sd[f"{key}.weight"]), "bias": to_numpy(sd[f"{key}.bias"])}


def convert_wan_checkpoint(state_dict: Mapping[str, Any], cfg: WanConfig) -> dict:
    """Official WAN state dict → the param pytree of ``models.wan.WanModel``
    (pass to ``build_wan(cfg, params=...)``)."""
    sd = dict(state_dict)

    # Conv3d patchify (O, C, pt, ph, pw) → Dense kernel (pt·ph·pw·C, O) in the
    # (pt, ph, pw, C) flattening order of WanModel.prepare.
    w = to_numpy(sd["patch_embedding.weight"])
    pe_kernel = w.transpose(2, 3, 4, 1, 0).reshape(-1, w.shape[0])
    p: dict[str, Any] = {
        "patch_embedding": {
            "kernel": pe_kernel,
            "bias": to_numpy(sd["patch_embedding.bias"]),
        },
        "text_in": _dense(sd, "text_embedding.0"),
        "text_hidden": _dense(sd, "text_embedding.2"),
        "time_in": _dense(sd, "time_embedding.0"),
        "time_hidden": _dense(sd, "time_embedding.2"),
        "time_projection": _dense(sd, "time_projection.1"),
        "head_proj": _dense(sd, "head.head"),
        "head_modulation": {"bias": to_numpy(sd["head.modulation"])},
    }
    if cfg.img_dim is not None:
        p["img_ln_in"] = _ln(sd, "img_emb.proj.0")
        p["img_in"] = _dense(sd, "img_emb.proj.1")
        p["img_hidden"] = _dense(sd, "img_emb.proj.3")
        p["img_ln_out"] = _ln(sd, "img_emb.proj.4")
    for i in range(cfg.depth):
        t = f"blocks.{i}"
        p[f"blocks_{i}"] = {
            "self_q": _dense(sd, f"{t}.self_attn.q"),
            "self_k": _dense(sd, f"{t}.self_attn.k"),
            "self_v": _dense(sd, f"{t}.self_attn.v"),
            "self_o": _dense(sd, f"{t}.self_attn.o"),
            "self_q_norm": _rms(sd, f"{t}.self_attn.norm_q"),
            "self_k_norm": _rms(sd, f"{t}.self_attn.norm_k"),
            "cross_q": _dense(sd, f"{t}.cross_attn.q"),
            "cross_k": _dense(sd, f"{t}.cross_attn.k"),
            "cross_v": _dense(sd, f"{t}.cross_attn.v"),
            "cross_o": _dense(sd, f"{t}.cross_attn.o"),
            "cross_q_norm": _rms(sd, f"{t}.cross_attn.norm_q"),
            "cross_k_norm": _rms(sd, f"{t}.cross_attn.norm_k"),
            "norm3": _ln(sd, f"{t}.norm3"),
            "ffn_in": _dense(sd, f"{t}.ffn.0"),
            "ffn_out": _dense(sd, f"{t}.ffn.2"),
            "modulation": to_numpy(sd[f"{t}.modulation"]),
        }
        if cfg.img_dim is not None:
            p[f"blocks_{i}"].update(
                cross_k_img=_dense(sd, f"{t}.cross_attn.k_img"),
                cross_v_img=_dense(sd, f"{t}.cross_attn.v_img"),
                cross_k_img_norm=_rms(sd, f"{t}.cross_attn.norm_k_img"),
            )
    return tree_to_jnp(p)
