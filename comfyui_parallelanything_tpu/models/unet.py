"""SD-family latent UNet (SD1.5 / SDXL) — flax.linen, NHWC, TPU-first.

Capability target: the reference's benchmark ladder runs SD-class UNets replicated
per device (BASELINE configs 1-2; the reference extracts UNet ctor kwargs like
``num_res_blocks``/``channel_mult``/``adm_in_channels``/``transformer_depth`` when
cloning, any_device_parallel.py:286-296 — those are exactly the knobs of this config).
This is a fresh TPU implementation, not a port: NHWC layout (TPU conv-friendly),
bf16 compute / f32 params by policy, attention through the pluggable backend
(ops/attention.py), everything shape-static under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.basic import timestep_embedding
from .api import DiffusionModel


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    num_res_blocks: int = 2
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    attention_levels: tuple[int, ...] = (0, 1, 2)
    transformer_depth: tuple[int, ...] = (1, 1, 1, 1)
    num_heads: int = 8
    context_dim: int = 768
    adm_in_channels: int | None = None  # SDXL pooled-text+size vector conditioning
    # Middle-block transformer depth override. None = derive from the deepest
    # encoder level (the SD1.5/SD2/SDXL-base pattern). The SDXL REFINER needs
    # it: no attention at its deepest encoder level but a depth-4 middle
    # transformer — underivable from the per-level tuple.
    transformer_depth_middle: int | None = None
    norm_groups: int = 32
    # Sampling parameterization the checkpoint was trained with ("eps" or "v");
    # carried on the config so samplers/nodes pick it up without a side channel
    # (ComfyUI keeps this in model_sampling the same way).
    prediction: str = "eps"
    # FreeU patch (Si et al. 2023; the host's FreeU/FreeU_V2 model patches):
    # (b1, b2, s1, s2, version) applied in the up path — backbone channels
    # scaled by b, skip connections low-pass-rescaled by s at the two
    # deepest-channel stages. None = off. Carried on the config (not a
    # runtime flag) so the patch composes with conversion/parallelize like
    # any other architecture knob: the patch node rebuilds the module around
    # the SAME params.
    freeu: tuple | None = None
    dtype: Any = jnp.bfloat16  # compute dtype; params stay f32


def sd15_config(**overrides) -> UNetConfig:
    return dataclasses.replace(UNetConfig(), **overrides)


def sd21_config(**overrides) -> UNetConfig:
    """SD2.x UNet: OpenCLIP-H context (1024) and fixed 64-dim heads. The 512
    base checkpoints are eps; the 768-v ones v-prediction — pass
    ``prediction="v"`` (or use the node family "sd21-v")."""
    base = UNetConfig(context_dim=1024, num_heads=-1)
    return dataclasses.replace(base, **overrides)


def sdxl_config(**overrides) -> UNetConfig:
    base = UNetConfig(
        model_channels=320,
        channel_mult=(1, 2, 4),
        attention_levels=(1, 2),
        transformer_depth=(0, 2, 10),
        num_heads=-1,  # SDXL uses fixed 64-dim heads; -1 → heads = channels // 64
        context_dim=2048,
        adm_in_channels=2816,
    )
    return dataclasses.replace(base, **overrides)


def sdxl_refiner_config(**overrides) -> UNetConfig:
    """SDXL-refiner UNet (sd_xl_refiner.yaml): 384 base channels, attention
    only at the middle two levels (depth 4) PLUS a depth-4 middle transformer,
    OpenCLIP-G-only context (1280), aesthetic-score adm (2560)."""
    base = UNetConfig(
        model_channels=384,
        channel_mult=(1, 2, 4, 4),
        attention_levels=(1, 2),
        transformer_depth=(0, 4, 4, 0),
        transformer_depth_middle=4,
        num_heads=-1,
        context_dim=1280,
        adm_in_channels=2560,
    )
    return dataclasses.replace(base, **overrides)


def _fourier_filter(x, threshold: int, scale: float):
    """FreeU's skip-connection low-frequency rescale: scale the centered
    ``2·threshold``-wide low-frequency box of the 2-D spectrum by ``scale``.
    FFT in f32 (TPU FFT is f32); cast back to the input dtype."""
    dtype = x.dtype
    xf = jnp.fft.fftshift(
        jnp.fft.fft2(x.astype(jnp.float32), axes=(1, 2)), axes=(1, 2)
    )
    B, H, W, C = x.shape
    cy, cx = H // 2, W // 2
    mask = jnp.ones((1, H, W, 1), jnp.float32)
    mask = mask.at[
        :, max(cy - threshold, 0):cy + threshold,
        max(cx - threshold, 0):cx + threshold, :,
    ].set(float(scale))
    out = jnp.fft.ifft2(
        jnp.fft.ifftshift(xf * mask, axes=(1, 2)), axes=(1, 2)
    ).real
    return out.astype(dtype)


def _apply_freeu(cfg: UNetConfig, h, skip):
    """FreeU on one up-block junction: when the backbone stream ``h`` sits at
    one of the two deepest channel widths, scale its first half-channels
    (constant ``b`` for v1; hidden-mean-modulated for v2 — the FreeU_V2
    improvement) and low-pass-rescale the skip by ``s``."""
    b1, b2, s1, s2, version = cfg.freeu
    C = h.shape[-1]
    # Stock keys the two stages on literal 4x and 2x the base width (1280/640
    # for both SD1.5 and SDXL) — NOT the channel_mult tail, which would
    # collide for SD1.5's (1, 2, 4, 4).
    stage = {cfg.model_channels * 4: (b1, s1),
             cfg.model_channels * 2: (b2, s2)}
    if C not in stage:
        return h, skip
    b, s = stage[C]
    half = C // 2
    if version >= 2:
        hidden_mean = jnp.mean(h.astype(jnp.float32), axis=-1, keepdims=True)
        dims = (1, 2, 3)
        h_min = jnp.min(hidden_mean, axis=dims, keepdims=True)
        h_max = jnp.max(hidden_mean, axis=dims, keepdims=True)
        hidden_mean = (hidden_mean - h_min) / jnp.maximum(h_max - h_min, 1e-8)
        scale = ((b - 1.0) * hidden_mean + 1.0).astype(h.dtype)
    else:
        scale = jnp.asarray(b, h.dtype)
    h = jnp.concatenate([h[..., :half] * scale, h[..., half:]], axis=-1)
    return h, _fourier_filter(skip, threshold=1, scale=s)


def middle_depth(cfg: UNetConfig) -> int:
    """Middle-block transformer depth — the ONE derivation shared by UNet2D,
    the checkpoint converter, and the ControlNet trunk (they must agree or
    conversion misindexes middle_block.{1,2})."""
    if cfg.transformer_depth_middle is not None:
        return cfg.transformer_depth_middle
    if len(cfg.channel_mult) - 1 in cfg.attention_levels:
        return cfg.transformer_depth[-1]
    return 0


def _heads_for(cfg: UNetConfig, channels: int) -> int:
    if cfg.num_heads == -1:
        return max(1, channels // 64)
    return cfg.num_heads


class ResBlock(nn.Module):
    cfg: UNetConfig
    out_ch: int

    @nn.compact
    def __call__(self, x, emb):
        cfg = self.cfg
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype)(h)
        emb_out = nn.Dense(self.out_ch, dtype=cfg.dtype)(nn.silu(emb))
        h = h + emb_out[:, None, None, :]
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype)(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=cfg.dtype)(x)
        return x + h


class TransformerBlock(nn.Module):
    """LN → self-attn → LN → cross-attn(context) → LN → GEGLU MLP, pre-norm residual."""

    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        heads = _heads_for(cfg, self.channels)
        head_dim = self.channels // heads

        def mha(q_in, kv_in, name):
            q = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_q")(q_in)
            k = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_k")(kv_in)
            v = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_v")(kv_in)
            o = attention(q, k, v)
            return nn.DenseGeneral(self.channels, axis=(-2, -1), dtype=cfg.dtype, name=f"{name}_o")(o)

        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + mha(h, h, "attn1")
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        ctx = h if context is None else context
        x = x + mha(h, ctx, "attn2")
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        gate = nn.Dense(self.channels * 8, dtype=cfg.dtype, name="ff_in")(h)
        a, b = jnp.split(gate, 2, axis=-1)
        # GEGLU with EXACT (erf) gelu — the ldm/diffusers convention for SD UNets
        # (FLUX-family models use tanh-approx; the two differ at ~1e-3, enough to
        # drift a 50-step sample).
        x = x + nn.Dense(self.channels, dtype=cfg.dtype, name="ff_out")(
            a * nn.gelu(b, approximate=False)
        )
        return x


class SpatialTransformer(nn.Module):
    cfg: UNetConfig
    channels: int
    depth: int

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        B, H, W, C = x.shape
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(x)
        h = nn.Conv(self.channels, (1, 1), dtype=cfg.dtype, name="proj_in")(h)
        h = h.reshape(B, H * W, self.channels)
        for i in range(self.depth):
            h = TransformerBlock(cfg, self.channels, name=f"block_{i}")(h, context)
        h = h.reshape(B, H, W, self.channels)
        h = nn.Conv(self.channels, (1, 1), dtype=cfg.dtype, name="proj_out")(h)
        return x + h


class Downsample(nn.Module):
    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (3, 3), strides=(2, 2), padding=1, dtype=self.cfg.dtype)(x)


class Upsample(nn.Module):
    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x):
        B, H, W, C = x.shape
        x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
        return nn.Conv(self.channels, (3, 3), padding=1, dtype=self.cfg.dtype)(x)


class UNet2D(nn.Module):
    """forward(x NHWC, timesteps (B,), context (B,S,D), y=(B,adm) for SDXL).

    ``control`` injects ControlNet residuals (models/controlnet.py): a dict
    with ``"input"`` (one NHWC residual per skip entry, added as each skip is
    consumed — the host UNet's hs.pop() + control pop convention) and
    ``"middle"`` (added to the middle-block output). Composed models build the
    dict inside the same jit program (``apply_control``), so it never crosses
    the kwargs-partitioning boundary as a python value.
    """

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x, timesteps, context=None, y=None, control=None,
                 **kwargs):
        cfg = self.cfg
        ch = cfg.model_channels
        t_emb = timestep_embedding(timesteps, ch).astype(cfg.dtype)
        emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="time_embed_0")(t_emb)
        emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="time_embed_2")(nn.silu(emb))
        if cfg.adm_in_channels is not None:
            if y is None:
                raise ValueError("this config requires vector conditioning `y`")
            y_emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="label_embed_0")(
                y.astype(cfg.dtype)
            )
            emb = emb + nn.Dense(ch * 4, dtype=cfg.dtype, name="label_embed_2")(
                nn.silu(y_emb)
            )

        x = x.astype(cfg.dtype)
        if context is not None:
            context = context.astype(cfg.dtype)

        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype, name="input_conv")(x)
        skips = [h]
        # -- input (down) blocks ---------------------------------------------------
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(cfg, out_ch, name=f"in_{level}_{i}_res")(h, emb)
                if level in cfg.attention_levels and cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        cfg, out_ch, cfg.transformer_depth[level], name=f"in_{level}_{i}_attn"
                    )(h, context)
                skips.append(h)
            if level != len(cfg.channel_mult) - 1:
                h = Downsample(cfg, out_ch, name=f"down_{level}")(h)
                skips.append(h)
        # -- middle ----------------------------------------------------------------
        mid_ch = ch * cfg.channel_mult[-1]
        mid_depth = middle_depth(cfg)
        h = ResBlock(cfg, mid_ch, name="mid_res1")(h, emb)
        if mid_depth > 0:
            h = SpatialTransformer(cfg, mid_ch, mid_depth, name="mid_attn")(h, context)
        h = ResBlock(cfg, mid_ch, name="mid_res2")(h, emb)
        ctrl_in: list = []
        if control is not None:
            mid_residuals = control.get("middle") or ()
            if mid_residuals:
                h = h + mid_residuals[0].astype(h.dtype)
            ctrl_in = list(control.get("input") or ())
            if ctrl_in and len(ctrl_in) != len(skips):
                raise ValueError(
                    f"control['input'] has {len(ctrl_in)} residuals for "
                    f"{len(skips)} skip connections — ControlNet/UNet config "
                    "mismatch"
                )
        # -- output (up) blocks ----------------------------------------------------
        for level in reversed(range(len(cfg.channel_mult))):
            out_ch = ch * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                skip = skips.pop()
                if ctrl_in:
                    skip = skip + ctrl_in.pop().astype(skip.dtype)
                if cfg.freeu is not None:
                    h, skip = _apply_freeu(cfg, h, skip)
                h = jnp.concatenate([h, skip], axis=-1)
                h = ResBlock(cfg, out_ch, name=f"out_{level}_{i}_res")(h, emb)
                if level in cfg.attention_levels and cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        cfg, out_ch, cfg.transformer_depth[level], name=f"out_{level}_{i}_attn"
                    )(h, context)
            if level != 0:
                h = Upsample(cfg, out_ch, name=f"up_{level}")(h)

        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype, name="out_norm")(h)
        h = nn.silu(h)
        h = nn.Conv(
            cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32, name="out_conv"
        )(h.astype(jnp.float32))
        return h


def apply_inpaint_conditioning(base: "DiffusionModel", mask, masked_latent):
    """Compose the 9-channel inpaint-model input convention into a
    DiffusionModel: every denoise step's input becomes
    ``concat([x, mask, masked_image_latent], channel)`` — the sd-inpainting
    checkpoint contract (4 + 1 + 4 channels). Like ``apply_control``, the
    conditioning channels ride the merged params pytree so the composition
    places/shards through ``parallelize`` and the whole step stays one jit
    program. ``mask`` is 1 where content is REGENERATED (latent resolution,
    (1|B, H, W, 1)); ``masked_latent`` is the VAE encode of the
    mask-blanked pixels."""
    merged = {
        "base": base.params,
        "mask": jnp.asarray(mask, jnp.float32),
        "masked": jnp.asarray(masked_latent, jnp.float32),
    }
    base_apply = base.apply

    def _bcast(a, batch):
        if a.ndim == 3:
            a = a[None]
        if a.shape[0] != batch:
            if a.shape[0] != 1:
                raise ValueError(
                    f"inpaint conditioning batch {a.shape[0]} != latent "
                    f"batch {batch}: pass ONE mask/masked-image (it "
                    "broadcasts); per-sample conditioning is not supported"
                )
            a = jnp.repeat(a, batch, axis=0)
        return a

    def apply(p, x, timesteps, context=None, **kw):
        m = _bcast(p["mask"], x.shape[0])
        ml = _bcast(p["masked"], x.shape[0])
        x_in = jnp.concatenate([x, m.astype(x.dtype), ml.astype(x.dtype)], -1)
        return base_apply(p["base"], x_in, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply, params=merged, name=f"{base.name}+inpaint",
        config=base.config,
    )


def unclip_adm(tags, adm_in_channels: int, rng=None,
               merge_augmentation: float = 0.05) -> jnp.ndarray:
    """SD2.x-unCLIP adm vector from ``unCLIPConditioning`` tags: each tag's
    CLIP image embeds are noise-augmented by its ``noise_augmentation`` level
    (DDPM q_sample over the squared-cosine alpha-bar table — the host's
    CLIPEmbeddingNoiseAugmentation, whose SD21UnclipL/H noise_aug_config sets
    ``beta_schedule: squaredcos_cap_v2``; identity data stats), concatenated
    with the sinusoidal embedding of that level, weighted by ``strength``, and
    summed; multiple tags re-augment the summed embeds at
    ``merge_augmentation`` (the host's noise_augment_merge). Returns
    (1, adm_in_channels) float32 — broadcast to the latent batch by the
    caller. The uncond half of CFG gets zeros (host SD21UNCLIP.encode_adm
    semantics for untagged conditioning). Host-surface parity: the reference
    registers only its own nodes and assumes the host provides unCLIP
    conditioning (any_device_parallel.py:1473-1483)."""
    import jax

    from ..ops.basic import timestep_embedding

    if rng is None:
        rng = jax.random.key(0)
    n = 1000
    # squaredcos_cap_v2: beta_t = 1 - bar((t+1)/T)/bar(t/T), capped at 0.999,
    # with bar(s) = cos²(((s + 0.008)/1.008)·π/2).
    import numpy as _np

    _t = _np.arange(n, dtype=_np.float64)

    def _bar(s):
        return _np.cos((s + 0.008) / 1.008 * _np.pi / 2.0) ** 2

    betas = _np.clip(1.0 - _bar((_t + 1) / n) / _bar(_t / n), 0.0, 0.999)
    acp = jnp.asarray(_np.cumprod(1.0 - betas), jnp.float32)

    def augment(emb, aug: float, key):
        level = int(round((n - 1) * max(0.0, min(1.0, aug))))
        noise = jax.random.normal(key, emb.shape, jnp.float32)
        noised = (
            jnp.sqrt(acp[level]) * emb + jnp.sqrt(1.0 - acp[level]) * noise
        )
        lvl = jnp.full((emb.shape[0],), float(level), jnp.float32)
        return noised, timestep_embedding(lvl, adm_in_channels - emb.shape[-1])

    outs = []
    for i, tag in enumerate(tags):
        emb = jnp.asarray(tag["embeds"], jnp.float32)
        if emb.ndim == 1:
            emb = emb[None]
        emb = emb[:1]  # one adm vector; stock iterates embeds row-wise
        noised, lvl_emb = augment(
            emb, float(tag.get("noise_augmentation", 0.0)),
            jax.random.fold_in(rng, i),
        )
        outs.append(
            jnp.concatenate([noised, lvl_emb], axis=-1)
            * float(tag.get("strength", 1.0))
        )
    y = sum(outs)
    if len(outs) > 1:
        emb_dim = jnp.asarray(tags[0]["embeds"]).shape[-1]
        noised, lvl_emb = augment(
            y[:, :emb_dim], merge_augmentation,
            jax.random.fold_in(rng, len(outs)),
        )
        y = jnp.concatenate([noised, lvl_emb], axis=-1)
    return y


def build_unet(
    cfg: UNetConfig,
    rng=None,
    sample_shape=(1, 64, 64, 4),
    name="sd-unet",
    params=None,
) -> DiffusionModel:
    """Build a UNet DiffusionModel; ``params`` skips initialization (load path)."""
    module = UNet2D(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        ctx = jnp.zeros((sample_shape[0], 77, cfg.context_dim), jnp.float32)
        kwargs = {}
        if cfg.adm_in_channels is not None:
            kwargs["y"] = jnp.zeros((sample_shape[0], cfg.adm_in_channels), jnp.float32)
        params = module.init(rng, x, t, ctx, **kwargs)["params"]

    def apply(params, x, timesteps, context=None, **kw):
        return module.apply({"params": params}, x, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply, params=params, name=name, config=cfg, block_lists=None
    )
