"""SD-family latent UNet (SD1.5 / SDXL) — flax.linen, NHWC, TPU-first.

Capability target: the reference's benchmark ladder runs SD-class UNets replicated
per device (BASELINE configs 1-2; the reference extracts UNet ctor kwargs like
``num_res_blocks``/``channel_mult``/``adm_in_channels``/``transformer_depth`` when
cloning, any_device_parallel.py:286-296 — those are exactly the knobs of this config).
This is a fresh TPU implementation, not a port: NHWC layout (TPU conv-friendly),
bf16 compute / f32 params by policy, attention through the pluggable backend
(ops/attention.py), everything shape-static under jit.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.basic import timestep_embedding
from .api import DiffusionModel


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    num_res_blocks: int = 2
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    attention_levels: tuple[int, ...] = (0, 1, 2)
    transformer_depth: tuple[int, ...] = (1, 1, 1, 1)
    num_heads: int = 8
    context_dim: int = 768
    adm_in_channels: int | None = None  # SDXL pooled-text+size vector conditioning
    # Middle-block transformer depth override. None = derive from the deepest
    # encoder level (the SD1.5/SD2/SDXL-base pattern). The SDXL REFINER needs
    # it: no attention at its deepest encoder level but a depth-4 middle
    # transformer — underivable from the per-level tuple.
    transformer_depth_middle: int | None = None
    norm_groups: int = 32
    # Sampling parameterization the checkpoint was trained with ("eps" or "v");
    # carried on the config so samplers/nodes pick it up without a side channel
    # (ComfyUI keeps this in model_sampling the same way).
    prediction: str = "eps"
    # FreeU patch (Si et al. 2023; the host's FreeU/FreeU_V2 model patches):
    # (b1, b2, s1, s2, version) applied in the up path — backbone channels
    # scaled by b, skip connections low-pass-rescaled by s at the two
    # deepest-channel stages. None = off. Carried on the config (not a
    # runtime flag) so the patch composes with conversion/parallelize like
    # any other architecture knob: the patch node rebuilds the module around
    # the SAME params.
    freeu: tuple | None = None
    dtype: Any = jnp.bfloat16  # compute dtype; params stay f32


def sd15_config(**overrides) -> UNetConfig:
    return dataclasses.replace(UNetConfig(), **overrides)


def sd21_config(**overrides) -> UNetConfig:
    """SD2.x UNet: OpenCLIP-H context (1024) and fixed 64-dim heads. The 512
    base checkpoints are eps; the 768-v ones v-prediction — pass
    ``prediction="v"`` (or use the node family "sd21-v")."""
    base = UNetConfig(context_dim=1024, num_heads=-1)
    return dataclasses.replace(base, **overrides)


def sdxl_config(**overrides) -> UNetConfig:
    base = UNetConfig(
        model_channels=320,
        channel_mult=(1, 2, 4),
        attention_levels=(1, 2),
        transformer_depth=(0, 2, 10),
        num_heads=-1,  # SDXL uses fixed 64-dim heads; -1 → heads = channels // 64
        context_dim=2048,
        adm_in_channels=2816,
    )
    return dataclasses.replace(base, **overrides)


def sdxl_refiner_config(**overrides) -> UNetConfig:
    """SDXL-refiner UNet (sd_xl_refiner.yaml): 384 base channels, attention
    only at the middle two levels (depth 4) PLUS a depth-4 middle transformer,
    OpenCLIP-G-only context (1280), aesthetic-score adm (2560)."""
    base = UNetConfig(
        model_channels=384,
        channel_mult=(1, 2, 4, 4),
        attention_levels=(1, 2),
        transformer_depth=(0, 4, 4, 0),
        transformer_depth_middle=4,
        num_heads=-1,
        context_dim=1280,
        adm_in_channels=2560,
    )
    return dataclasses.replace(base, **overrides)


def _fourier_filter(x, threshold: int, scale: float):
    """FreeU's skip-connection low-frequency rescale: scale the centered
    ``2·threshold``-wide low-frequency box of the 2-D spectrum by ``scale``.
    FFT in f32 (TPU FFT is f32); cast back to the input dtype."""
    dtype = x.dtype
    xf = jnp.fft.fftshift(
        jnp.fft.fft2(x.astype(jnp.float32), axes=(1, 2)), axes=(1, 2)
    )
    B, H, W, C = x.shape
    cy, cx = H // 2, W // 2
    mask = jnp.ones((1, H, W, 1), jnp.float32)
    mask = mask.at[
        :, max(cy - threshold, 0):cy + threshold,
        max(cx - threshold, 0):cx + threshold, :,
    ].set(float(scale))
    out = jnp.fft.ifft2(
        jnp.fft.ifftshift(xf * mask, axes=(1, 2)), axes=(1, 2)
    ).real
    return out.astype(dtype)


def _apply_freeu(cfg: UNetConfig, h, skip):
    """FreeU on one up-block junction: when the backbone stream ``h`` sits at
    one of the two deepest channel widths, scale its first half-channels
    (constant ``b`` for v1; hidden-mean-modulated for v2 — the FreeU_V2
    improvement) and low-pass-rescale the skip by ``s``."""
    b1, b2, s1, s2, version = cfg.freeu
    C = h.shape[-1]
    # Stock keys the two stages on literal 4x and 2x the base width (1280/640
    # for both SD1.5 and SDXL) — NOT the channel_mult tail, which would
    # collide for SD1.5's (1, 2, 4, 4).
    stage = {cfg.model_channels * 4: (b1, s1),
             cfg.model_channels * 2: (b2, s2)}
    if C not in stage:
        return h, skip
    b, s = stage[C]
    half = C // 2
    if version >= 2:
        hidden_mean = jnp.mean(h.astype(jnp.float32), axis=-1, keepdims=True)
        dims = (1, 2, 3)
        h_min = jnp.min(hidden_mean, axis=dims, keepdims=True)
        h_max = jnp.max(hidden_mean, axis=dims, keepdims=True)
        hidden_mean = (hidden_mean - h_min) / jnp.maximum(h_max - h_min, 1e-8)
        scale = ((b - 1.0) * hidden_mean + 1.0).astype(h.dtype)
    else:
        scale = jnp.asarray(b, h.dtype)
    h = jnp.concatenate([h[..., :half] * scale, h[..., half:]], axis=-1)
    return h, _fourier_filter(skip, threshold=1, scale=s)


def middle_depth(cfg: UNetConfig) -> int:
    """Middle-block transformer depth — the ONE derivation shared by UNet2D,
    the checkpoint converter, and the ControlNet trunk (they must agree or
    conversion misindexes middle_block.{1,2})."""
    if cfg.transformer_depth_middle is not None:
        return cfg.transformer_depth_middle
    if len(cfg.channel_mult) - 1 in cfg.attention_levels:
        return cfg.transformer_depth[-1]
    return 0


def _heads_for(cfg: UNetConfig, channels: int) -> int:
    if cfg.num_heads == -1:
        return max(1, channels // 64)
    return cfg.num_heads


class ResBlock(nn.Module):
    cfg: UNetConfig
    out_ch: int

    @nn.compact
    def __call__(self, x, emb):
        cfg = self.cfg
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype)(h)
        emb_out = nn.Dense(self.out_ch, dtype=cfg.dtype)(nn.silu(emb))
        h = h + emb_out[:, None, None, :]
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_ch, (3, 3), padding=1, dtype=cfg.dtype)(h)
        if x.shape[-1] != self.out_ch:
            x = nn.Conv(self.out_ch, (1, 1), dtype=cfg.dtype)(x)
        return x + h


class TransformerBlock(nn.Module):
    """LN → self-attn → LN → cross-attn(context) → LN → GEGLU MLP, pre-norm residual."""

    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        heads = _heads_for(cfg, self.channels)
        head_dim = self.channels // heads

        def mha(q_in, kv_in, name):
            q = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_q")(q_in)
            k = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_k")(kv_in)
            v = nn.DenseGeneral((heads, head_dim), use_bias=False, dtype=cfg.dtype, name=f"{name}_v")(kv_in)
            o = attention(q, k, v)
            return nn.DenseGeneral(self.channels, axis=(-2, -1), dtype=cfg.dtype, name=f"{name}_o")(o)

        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        x = x + mha(h, h, "attn1")
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        ctx = h if context is None else context
        x = x + mha(h, ctx, "attn2")
        h = nn.LayerNorm(dtype=cfg.dtype)(x)
        gate = nn.Dense(self.channels * 8, dtype=cfg.dtype, name="ff_in")(h)
        a, b = jnp.split(gate, 2, axis=-1)
        # GEGLU with EXACT (erf) gelu — the ldm/diffusers convention for SD UNets
        # (FLUX-family models use tanh-approx; the two differ at ~1e-3, enough to
        # drift a 50-step sample).
        x = x + nn.Dense(self.channels, dtype=cfg.dtype, name="ff_out")(
            a * nn.gelu(b, approximate=False)
        )
        return x


class SpatialTransformer(nn.Module):
    cfg: UNetConfig
    channels: int
    depth: int

    @nn.compact
    def __call__(self, x, context):
        cfg = self.cfg
        B, H, W, C = x.shape
        h = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)(x)
        h = nn.Conv(self.channels, (1, 1), dtype=cfg.dtype, name="proj_in")(h)
        h = h.reshape(B, H * W, self.channels)
        for i in range(self.depth):
            h = TransformerBlock(cfg, self.channels, name=f"block_{i}")(h, context)
        h = h.reshape(B, H, W, self.channels)
        h = nn.Conv(self.channels, (1, 1), dtype=cfg.dtype, name="proj_out")(h)
        return x + h


class Downsample(nn.Module):
    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x):
        return nn.Conv(self.channels, (3, 3), strides=(2, 2), padding=1, dtype=self.cfg.dtype)(x)


class Upsample(nn.Module):
    cfg: UNetConfig
    channels: int

    @nn.compact
    def __call__(self, x):
        B, H, W, C = x.shape
        x = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
        return nn.Conv(self.channels, (3, 3), padding=1, dtype=self.cfg.dtype)(x)


def _has_attn(cfg: UNetConfig, level: int) -> bool:
    return level in cfg.attention_levels and cfg.transformer_depth[level] > 0


def _input_schedule(cfg: UNetConfig) -> list[tuple[int, int]]:
    """(level, i) of every input (down) block, in execution order."""
    return [
        (level, i)
        for level in range(len(cfg.channel_mult))
        for i in range(cfg.num_res_blocks)
    ]


def _output_schedule(cfg: UNetConfig) -> list[tuple[int, int]]:
    """(level, i) of every output (up) block, in execution order."""
    return [
        (level, i)
        for level in reversed(range(len(cfg.channel_mult)))
        for i in range(cfg.num_res_blocks + 1)
    ]


def _skip_base(cfg: UNetConfig, level: int) -> int:
    """Index of the first skip pushed by ``level`` (skip_0 = the input conv;
    each earlier level pushed num_res_blocks skips plus one for its
    downsample)."""
    last = len(cfg.channel_mult) - 1
    return 1 + sum(
        cfg.num_res_blocks + (1 if m != last else 0) for m in range(level)
    )


def _total_skips(cfg: UNetConfig) -> int:
    return _skip_base(cfg, len(cfg.channel_mult))


class UNet2D(nn.Module):
    """forward(x NHWC, timesteps (B,), context (B,S,D), y=(B,adm) for SDXL).

    ``control`` injects ControlNet residuals (models/controlnet.py): a dict
    with ``"input"`` (one NHWC residual per skip entry, added as each skip is
    consumed — the host UNet's hs.pop() + control pop convention) and
    ``"middle"`` (added to the middle-block output). Composed models build the
    dict inside the same jit program (``apply_control``), so it never crosses
    the kwargs-partitioning boundary as a python value.

    Structured setup-style as a staged forward — prepare → input blocks →
    middle → output blocks → finalize — so the same module serves the plain
    jitted apply AND the ``PipelineSpec`` decomposition (batch==1 block
    placement and the weight-streaming executor, parallel/streaming.py). The
    carry is a flat dict: ``h``/``emb``/``context`` plus ``skip_{i}`` entries
    (the skip stack, indexed statically per cfg) and optional ``ctrl_*``
    residuals; param names are IDENTICAL to the previous inline layout, so
    checkpoints convert unchanged.
    """

    cfg: UNetConfig

    def setup(self):
        cfg = self.cfg
        ch = cfg.model_channels
        self.time_embed_0 = nn.Dense(ch * 4, dtype=cfg.dtype)
        self.time_embed_2 = nn.Dense(ch * 4, dtype=cfg.dtype)
        if cfg.adm_in_channels is not None:
            self.label_embed_0 = nn.Dense(ch * 4, dtype=cfg.dtype)
            self.label_embed_2 = nn.Dense(ch * 4, dtype=cfg.dtype)
        self.input_conv = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype)
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                setattr(self, f"in_{level}_{i}_res", ResBlock(cfg, out_ch))
                if _has_attn(cfg, level):
                    setattr(
                        self, f"in_{level}_{i}_attn",
                        SpatialTransformer(
                            cfg, out_ch, cfg.transformer_depth[level]
                        ),
                    )
            if level != len(cfg.channel_mult) - 1:
                setattr(self, f"down_{level}", Downsample(cfg, out_ch))
        mid_ch = ch * cfg.channel_mult[-1]
        self.mid_res1 = ResBlock(cfg, mid_ch)
        if middle_depth(cfg) > 0:
            self.mid_attn = SpatialTransformer(cfg, mid_ch, middle_depth(cfg))
        self.mid_res2 = ResBlock(cfg, mid_ch)
        for level in range(len(cfg.channel_mult)):
            out_ch = ch * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                setattr(self, f"out_{level}_{i}_res", ResBlock(cfg, out_ch))
                if _has_attn(cfg, level):
                    setattr(
                        self, f"out_{level}_{i}_attn",
                        SpatialTransformer(
                            cfg, out_ch, cfg.transformer_depth[level]
                        ),
                    )
            if level != 0:
                setattr(self, f"up_{level}", Upsample(cfg, out_ch))
        self.out_norm = nn.GroupNorm(num_groups=cfg.norm_groups, dtype=cfg.dtype)
        self.out_conv = nn.Conv(
            cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32
        )

    # -- staged forward (the PipelineSpec decomposition) -----------------------

    def prepare(self, x, timesteps, context=None, y=None, control=None,
                **kwargs):
        """Embeddings + input conv on the lead device; seeds the carry with
        skip_0 and flattens any ControlNet residuals into ``ctrl_*`` entries
        so the carry stays a flat dict of arrays."""
        cfg = self.cfg
        ch = cfg.model_channels
        t_emb = timestep_embedding(timesteps, ch).astype(cfg.dtype)
        emb = self.time_embed_0(t_emb)
        emb = self.time_embed_2(nn.silu(emb))
        if cfg.adm_in_channels is not None:
            if y is None:
                raise ValueError("this config requires vector conditioning `y`")
            y_emb = self.label_embed_0(y.astype(cfg.dtype))
            emb = emb + self.label_embed_2(nn.silu(y_emb))
        x = x.astype(cfg.dtype)
        if context is not None:
            context = context.astype(cfg.dtype)
        h = self.input_conv(x)
        carry = {"h": h, "emb": emb, "context": context, "skip_0": h}
        if control is not None:
            for j, res in enumerate(control.get("input") or ()):
                carry[f"ctrl_in_{j}"] = res
            mid_residuals = control.get("middle") or ()
            if mid_residuals:
                carry["ctrl_mid"] = mid_residuals[0]
        return carry

    def input_step(self, carry, level: int, i: int):
        cfg = self.cfg
        h = getattr(self, f"in_{level}_{i}_res")(carry["h"], carry["emb"])
        if _has_attn(cfg, level):
            h = getattr(self, f"in_{level}_{i}_attn")(h, carry["context"])
        out = dict(carry)
        idx = _skip_base(cfg, level) + i
        out[f"skip_{idx}"] = h
        if i == cfg.num_res_blocks - 1 and level != len(cfg.channel_mult) - 1:
            h = getattr(self, f"down_{level}")(h)
            out[f"skip_{idx + 1}"] = h
        out["h"] = h
        return out

    def middle_step(self, carry):
        cfg = self.cfg
        h = self.mid_res1(carry["h"], carry["emb"])
        if middle_depth(cfg) > 0:
            h = self.mid_attn(h, carry["context"])
        h = self.mid_res2(h, carry["emb"])
        if "ctrl_mid" in carry:
            h = h + carry["ctrl_mid"].astype(h.dtype)
        n_ctrl = sum(1 for k in carry if k.startswith("ctrl_in_"))
        n_skips = sum(1 for k in carry if k.startswith("skip_"))
        if n_ctrl and n_ctrl != n_skips:
            raise ValueError(
                f"control['input'] has {n_ctrl} residuals for "
                f"{n_skips} skip connections — ControlNet/UNet config "
                "mismatch"
            )
        return {**carry, "h": h}

    def output_step(self, carry, level: int, i: int):
        cfg = self.cfg
        # j-th output block consumes the skip stack LIFO (hs.pop() parity).
        j = (
            (len(cfg.channel_mult) - 1 - level) * (cfg.num_res_blocks + 1) + i
        )
        idx = _total_skips(cfg) - 1 - j
        out = dict(carry)
        skip = out.pop(f"skip_{idx}")
        ctrl = out.pop(f"ctrl_in_{idx}", None)
        if ctrl is not None:
            skip = skip + ctrl.astype(skip.dtype)
        h = out["h"]
        if cfg.freeu is not None:
            h, skip = _apply_freeu(cfg, h, skip)
        h = jnp.concatenate([h, skip], axis=-1)
        h = getattr(self, f"out_{level}_{i}_res")(h, out["emb"])
        if _has_attn(cfg, level):
            h = getattr(self, f"out_{level}_{i}_attn")(h, out["context"])
        if i == cfg.num_res_blocks and level != 0:
            h = getattr(self, f"up_{level}")(h)
        out["h"] = h
        return out

    def finalize(self, carry, out_shape: tuple[int, ...]):
        """Final norm + projection (lead device); ``out_shape`` is the
        PipelineSpec finalize contract — the UNet's geometry already rides
        the carry, so it is unused here."""
        del out_shape
        h = self.out_norm(carry["h"])
        h = nn.silu(h)
        return self.out_conv(h.astype(jnp.float32))

    def __call__(self, x, timesteps, context=None, y=None, control=None,
                 **kwargs):
        cfg = self.cfg
        carry = self.prepare(x, timesteps, context, y=y, control=control)
        for level, i in _input_schedule(cfg):
            carry = self.input_step(carry, level, i)
        carry = self.middle_step(carry)
        for level, i in _output_schedule(cfg):
            carry = self.output_step(carry, level, i)
        return self.finalize(carry, x.shape)


def apply_inpaint_conditioning(base: "DiffusionModel", mask, masked_latent):
    """Compose the 9-channel inpaint-model input convention into a
    DiffusionModel: every denoise step's input becomes
    ``concat([x, mask, masked_image_latent], channel)`` — the sd-inpainting
    checkpoint contract (4 + 1 + 4 channels). Like ``apply_control``, the
    conditioning channels ride the merged params pytree so the composition
    places/shards through ``parallelize`` and the whole step stays one jit
    program. ``mask`` is 1 where content is REGENERATED (latent resolution,
    (1|B, H, W, 1)); ``masked_latent`` is the VAE encode of the
    mask-blanked pixels."""
    merged = {
        "base": base.params,
        "mask": jnp.asarray(mask, jnp.float32),
        "masked": jnp.asarray(masked_latent, jnp.float32),
    }
    base_apply = base.apply

    def _bcast(a, batch):
        if a.ndim == 3:
            a = a[None]
        if a.shape[0] != batch:
            if a.shape[0] != 1:
                raise ValueError(
                    f"inpaint conditioning batch {a.shape[0]} != latent "
                    f"batch {batch}: pass ONE mask/masked-image (it "
                    "broadcasts); per-sample conditioning is not supported"
                )
            a = jnp.repeat(a, batch, axis=0)
        return a

    def apply(p, x, timesteps, context=None, **kw):
        m = _bcast(p["mask"], x.shape[0])
        ml = _bcast(p["masked"], x.shape[0])
        x_in = jnp.concatenate([x, m.astype(x.dtype), ml.astype(x.dtype)], -1)
        return base_apply(p["base"], x_in, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply, params=merged, name=f"{base.name}+inpaint",
        config=base.config,
    )


def unclip_adm(tags, adm_in_channels: int, rng=None,
               merge_augmentation: float = 0.05) -> jnp.ndarray:
    """SD2.x-unCLIP adm vector from ``unCLIPConditioning`` tags: each tag's
    CLIP image embeds are noise-augmented by its ``noise_augmentation`` level
    (DDPM q_sample over the squared-cosine alpha-bar table — the host's
    CLIPEmbeddingNoiseAugmentation, whose SD21UnclipL/H noise_aug_config sets
    ``beta_schedule: squaredcos_cap_v2``; identity data stats), concatenated
    with the sinusoidal embedding of that level, weighted by ``strength``, and
    summed; multiple tags re-augment the summed embeds at
    ``merge_augmentation`` (the host's noise_augment_merge). Returns
    (1, adm_in_channels) float32 — broadcast to the latent batch by the
    caller. The uncond half of CFG gets zeros (host SD21UNCLIP.encode_adm
    semantics for untagged conditioning). Host-surface parity: the reference
    registers only its own nodes and assumes the host provides unCLIP
    conditioning (any_device_parallel.py:1473-1483)."""
    import jax

    from ..ops.basic import timestep_embedding

    if rng is None:
        rng = jax.random.key(0)
    n = 1000
    # squaredcos_cap_v2: beta_t = 1 - bar((t+1)/T)/bar(t/T), capped at 0.999,
    # with bar(s) = cos²(((s + 0.008)/1.008)·π/2).
    import numpy as _np

    _t = _np.arange(n, dtype=_np.float64)

    def _bar(s):
        return _np.cos((s + 0.008) / 1.008 * _np.pi / 2.0) ** 2

    betas = _np.clip(1.0 - _bar((_t + 1) / n) / _bar(_t / n), 0.0, 0.999)
    acp = jnp.asarray(_np.cumprod(1.0 - betas), jnp.float32)

    def augment(emb, aug: float, key):
        level = int(round((n - 1) * max(0.0, min(1.0, aug))))
        noise = jax.random.normal(key, emb.shape, jnp.float32)
        noised = (
            jnp.sqrt(acp[level]) * emb + jnp.sqrt(1.0 - acp[level]) * noise
        )
        lvl = jnp.full((emb.shape[0],), float(level), jnp.float32)
        return noised, timestep_embedding(lvl, adm_in_channels - emb.shape[-1])

    outs = []
    for i, tag in enumerate(tags):
        emb = jnp.asarray(tag["embeds"], jnp.float32)
        if emb.ndim == 1:
            emb = emb[None]
        emb = emb[:1]  # one adm vector; stock iterates embeds row-wise
        noised, lvl_emb = augment(
            emb, float(tag.get("noise_augmentation", 0.0)),
            jax.random.fold_in(rng, i),
        )
        outs.append(
            jnp.concatenate([noised, lvl_emb], axis=-1)
            * float(tag.get("strength", 1.0))
        )
    y = sum(outs)
    if len(outs) > 1:
        emb_dim = jnp.asarray(tags[0]["embeds"]).shape[-1]
        noised, lvl_emb = augment(
            y[:, :emb_dim], merge_augmentation,
            jax.random.fold_in(rng, len(outs)),
        )
        y = jnp.concatenate([noised, lvl_emb], axis=-1)
    return y


def _unet_pipeline_spec(module: "UNet2D", cfg: UNetConfig):
    """Stage decomposition of the UNet forward: embeddings/input conv on the
    lead device, one segment per input/middle/output block, final
    norm/projection on the lead. The skip connections ride the carry as
    statically-indexed ``skip_{i}`` entries, so the carry structure at every
    segment boundary is fixed per cfg — what both batch==1 block placement
    (parallel/pipeline.py) and the weight-streaming executor
    (parallel/streaming.py) need. The reference never pipelines UNets (its
    block-list walk finds no ['double_blocks', ...] name,
    any_device_parallel.py:1156-1166); the staged form here is what lets an
    SD-family model stream when its weights exceed HBM."""
    from .api import PipelineSegment, PipelineSpec

    def prepare(params, x, t, context=None, **kw):
        return module.apply(
            {"params": params}, x, t, context, method=UNet2D.prepare, **kw
        )

    def make_input(level, i):
        def fn(params, carry):
            return module.apply(
                {"params": params}, carry, level, i, method=UNet2D.input_step
            )

        return fn

    def middle(params, carry):
        return module.apply({"params": params}, carry, method=UNet2D.middle_step)

    def make_output(level, i):
        def fn(params, carry):
            return module.apply(
                {"params": params}, carry, level, i, method=UNet2D.output_step
            )

        return fn

    def finalize(params, carry, out_shape):
        return module.apply(
            {"params": params}, carry, out_shape, method=UNet2D.finalize
        )

    last = len(cfg.channel_mult) - 1
    segments = []
    for level, i in _input_schedule(cfg):
        keys = [f"in_{level}_{i}_res"]
        if _has_attn(cfg, level):
            keys.append(f"in_{level}_{i}_attn")
        if i == cfg.num_res_blocks - 1 and level != last:
            keys.append(f"down_{level}")
        segments.append(
            PipelineSegment(tuple(keys), make_input(level, i),
                            f"input[{level}.{i}]")
        )
    mid_keys = ["mid_res1", "mid_res2"]
    if middle_depth(cfg) > 0:
        mid_keys.insert(1, "mid_attn")
    segments.append(PipelineSegment(tuple(mid_keys), middle, "middle"))
    for level, i in _output_schedule(cfg):
        keys = [f"out_{level}_{i}_res"]
        if _has_attn(cfg, level):
            keys.append(f"out_{level}_{i}_attn")
        if i == cfg.num_res_blocks and level != 0:
            keys.append(f"up_{level}")
        segments.append(
            PipelineSegment(tuple(keys), make_output(level, i),
                            f"output[{level}.{i}]")
        )

    prepare_keys = ["time_embed_0", "time_embed_2", "input_conv"]
    if cfg.adm_in_channels is not None:
        prepare_keys[2:2] = ["label_embed_0", "label_embed_2"]
    return PipelineSpec(
        prepare_keys=tuple(prepare_keys),
        prepare=prepare,
        segments=tuple(segments),
        finalize_keys=("out_norm", "out_conv"),
        finalize=finalize,
    )


def build_unet(
    cfg: UNetConfig,
    rng=None,
    sample_shape=(1, 64, 64, 4),
    name="sd-unet",
    params=None,
) -> DiffusionModel:
    """Build a UNet DiffusionModel; ``params`` skips initialization (load path)."""
    module = UNet2D(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        ctx = jnp.zeros((sample_shape[0], 77, cfg.context_dim), jnp.float32)
        kwargs = {}
        if cfg.adm_in_channels is not None:
            kwargs["y"] = jnp.zeros((sample_shape[0], cfg.adm_in_channels), jnp.float32)
        params = module.init(rng, x, t, ctx, **kwargs)["params"]

    def apply(params, x, timesteps, context=None, **kw):
        return module.apply({"params": params}, x, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply, params=params, name=name, config=cfg, block_lists=None,
        pipeline_spec=_unet_pipeline_spec(module, cfg),
    )
