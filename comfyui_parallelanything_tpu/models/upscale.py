"""ESRGAN-family image upscalers (RRDBNet) — flax.linen, NHWC, TPU-first.

The reference's host ships UpscaleModelLoader/ImageUpscaleWithModel (the
hi-res-fix second stage most exported workflows use); the reference wraps the
diffusion model and leaves upscalers to the host. Standalone, this module is
that family: the public RRDBNet topology (ESRGAN/RealESRGAN lineage) — dense
residual blocks at 0.2 residual scaling, nearest-2x + conv upsampling — as a
pure-apply flax module, with the two public checkpoint layouts converted
(modern ``conv_first/body.N.rdbM.convK`` keys and the legacy
``model.0/model.1.sub.N`` sequential naming).

TPU notes: convs run NHWC in the configured dtype (bf16 by default on TPU,
f32 in tests); the whole net is one jit program per image shape. Large images
upscale in overlapping tiles blended linearly (``upscale_image`` tile path) —
bounded activation memory at any resolution, no seams.
"""

from __future__ import annotations

import dataclasses
import re
from collections.abc import Mapping
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class UpscaleConfig:
    nf: int = 64           # feature width
    nb: int = 23           # RRDB blocks
    gc: int = 32           # dense growth channels
    scale: int = 4         # output scale: 4, 2 (pixel-unshuffle in), or 1
    in_channels: int = 3
    out_channels: int = 3
    dtype: Any = jnp.float32


def _lrelu(x):
    return nn.leaky_relu(x, negative_slope=0.2)


class _RDB(nn.Module):
    cfg: UpscaleConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        feats = [x]
        for i in range(4):
            out = nn.Conv(cfg.gc, (3, 3), padding=1, dtype=cfg.dtype,
                          name=f"conv{i + 1}")(jnp.concatenate(feats, -1))
            feats.append(_lrelu(out))
        out = nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                      name="conv5")(jnp.concatenate(feats, -1))
        return x + 0.2 * out


class _RRDB(nn.Module):
    cfg: UpscaleConfig

    @nn.compact
    def __call__(self, x):
        h = _RDB(self.cfg, name="rdb1")(x)
        h = _RDB(self.cfg, name="rdb2")(h)
        h = _RDB(self.cfg, name="rdb3")(h)
        return x + 0.2 * h


def _nearest2x(x):
    return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)


def _pixel_unshuffle(x, s: int):
    """NHWC space→depth with torch's channel order (C-major: out channel
    c·s² + i·s + j) — RealESRGAN x2/x1 conv_first weights were trained
    against torch.pixel_unshuffle, so the order is part of the checkpoint
    contract (pinned against torch in tests/test_upscale.py)."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // s, s, W // s, s, C)
    return x.transpose(0, 1, 3, 5, 2, 4).reshape(B, H // s, W // s, s * s * C)


class RRDBNet(nn.Module):
    """forward(image NHWC in [0, 1]) → upscaled image, clipped to [0, 1]."""

    cfg: UpscaleConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        # RealESRGAN x2/x1 variants pixel-unshuffle the input (space→depth) so
        # the 4x trunk yields a net 2x/1x — the conv_first width encodes it.
        shuffle = {4: 1, 2: 2, 1: 4}[cfg.scale]
        if shuffle > 1:
            x = _pixel_unshuffle(x, shuffle)
        h = nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                    name="conv_first")(x)
        trunk = h
        for i in range(cfg.nb):
            trunk = _RRDB(cfg, name=f"body_{i}")(trunk)
        h = h + nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                        name="conv_body")(trunk)
        h = _lrelu(nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                           name="conv_up1")(_nearest2x(h)))
        h = _lrelu(nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                           name="conv_up2")(_nearest2x(h)))
        h = _lrelu(nn.Conv(cfg.nf, (3, 3), padding=1, dtype=cfg.dtype,
                           name="conv_hr")(h))
        h = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_last")(h.astype(jnp.float32))
        return jnp.clip(h, 0.0, 1.0)


@dataclasses.dataclass
class UpscaleModel:
    """An image upscaler as data: pure apply + weights (the DiffusionModel
    pattern, models/api.py, for the upscaler family)."""

    apply: Any
    params: Any
    cfg: UpscaleConfig
    name: str = "upscaler"

    def __call__(self, image):
        if not hasattr(self, "_jit"):
            object.__setattr__(self, "_jit", jax.jit(self.apply))
        return self._jit(self.params, image)


def build_upscaler(cfg: UpscaleConfig, rng=None, params=None,
                   name="upscaler") -> UpscaleModel:
    module = RRDBNet(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        hw = 8 * {4: 1, 2: 2, 1: 4}[cfg.scale]
        params = module.init(
            rng, jnp.zeros((1, hw, hw, cfg.in_channels), jnp.float32)
        )["params"]

    def apply(p, x):
        return module.apply({"params": p}, x)

    return UpscaleModel(apply=apply, params=params, cfg=cfg, name=name)


# ---------------------------------------------------------------------------
# Checkpoint conversion (both public layouts)
# ---------------------------------------------------------------------------

_OLD_HEAD = {
    "model.0": "conv_first",
    "model.3": "conv_up1",
    "model.6": "conv_up2",
    "model.8": "conv_hr",
    "model.10": "conv_last",
}


def _normalize_esrgan_keys(sd: Mapping[str, Any]) -> dict:
    """Legacy ESRGAN sequential naming → modern RRDBNet keys.

    ``model.0``→conv_first; ``model.1.sub.{i}.RDB{k}.conv{j}.0``→
    ``body.{i}.rdb{k}.conv{j}``; ``model.1.sub.{nb}``→conv_body (the trunk
    conv rides the last sub index); ``model.3/6/8/10``→up1/up2/hr/last."""
    if not any(k.startswith("model.") for k in sd):
        return dict(sd)
    out: dict = {}
    sub_idx = [int(m.group(1)) for k in sd
               if (m := re.match(r"model\.1\.sub\.(\d+)\.", k))]
    trunk = max(sub_idx) if sub_idx else 0
    for k, v in sd.items():
        m = re.match(r"model\.1\.sub\.(\d+)\.(.*)", k)
        if m:
            i, rest = int(m.group(1)), m.group(2)
            if i == trunk:
                out[f"conv_body.{rest}"] = v
                continue
            rest = re.sub(r"RDB(\d)\.conv(\d)\.0\.", r"rdb\1.conv\2.", rest)
            out[f"body.{i}.{rest}"] = v
            continue
        for old, new in _OLD_HEAD.items():
            if k.startswith(old + "."):
                out[new + k[len(old):]] = v
                break
        else:
            out[k] = v
    leftovers = sorted(k for k in out if k.startswith("model."))
    if leftovers:
        # The legacy head table above is the x4 layout; other scales put the
        # upsample/HR/last convs at different sequential indices.
        raise ValueError(
            "legacy ESRGAN layout with unrecognized head keys "
            f"{leftovers[:4]} — only the x4 sequential layout "
            "(model.3/6/8/10) is mapped; re-save the model in the modern "
            "RRDBNet key layout (conv_first/body.N/...)"
        )
    return out


def sniff_upscale_config(sd: Mapping[str, Any]) -> UpscaleConfig:
    """Infer (nf, nb, gc, scale) from a normalized RRDBNet state dict: widths
    from conv_first/rdb conv1, depth from the body indices, scale from the
    pixel-unshuffle factor encoded in conv_first's input width."""
    w_first = np.asarray(sd["conv_first.weight"])
    nf, in_w = int(w_first.shape[0]), int(w_first.shape[1])
    gc = int(np.asarray(sd["body.0.rdb1.conv1.weight"]).shape[0])
    nb = 1 + max(
        int(m.group(1)) for k in sd if (m := re.match(r"body\.(\d+)\.", k))
    )
    out_ch = int(np.asarray(sd["conv_last.weight"]).shape[0])
    # conv_first's input width encodes in_channels × pixel-unshuffle²:
    # x4 models see raw pixels (factor 1), x2 unshuffle by 2 (factor 4),
    # x1 by 4 (factor 16). Only the known 1/3-channel pairs are accepted;
    # widths outside the table raise instead of guessing a divisor. Widths
    # that COLLIDE with a table entry (a 4-channel x4 sniffs as 1-channel
    # x2 at width 4; 4-channel x2 as 1-channel x1 at width 16) cannot be
    # told apart from the state dict — such variants need an explicit
    # UpscaleConfig.
    known = {1: (1, 4), 3: (3, 4), 4: (1, 2), 12: (3, 2), 16: (1, 1),
             48: (3, 1)}
    if in_w not in known:
        raise ValueError(
            f"unrecognized RRDBNet conv_first input width {in_w}: expected "
            "in_channels 1 or 3 with pixel-unshuffle factor 1/4/16 "
            f"(widths {sorted(known)}); pass an explicit UpscaleConfig for "
            "nonstandard variants"
        )
    base_in, scale = known[in_w]
    return UpscaleConfig(nf=nf, nb=nb, gc=gc, scale=scale,
                         in_channels=base_in, out_channels=out_ch)


def convert_upscale_checkpoint(sd: Mapping[str, Any],
                               cfg: UpscaleConfig | None = None):
    """Normalized-or-legacy RRDBNet state dict → (params, cfg)."""
    from .convert import conv_kernel, to_numpy, tree_to_jnp

    sd = _normalize_esrgan_keys(sd)
    if cfg is None:
        cfg = sniff_upscale_config(sd)

    def conv(key):
        out = {"kernel": conv_kernel(sd[f"{key}.weight"])}
        if f"{key}.bias" in sd:
            out["bias"] = to_numpy(sd[f"{key}.bias"])
        return out

    p: dict = {k: conv(k) for k in
               ("conv_first", "conv_body", "conv_up1", "conv_up2",
                "conv_hr", "conv_last")}
    for i in range(cfg.nb):
        p[f"body_{i}"] = {
            f"rdb{k}": {f"conv{j}": conv(f"body.{i}.rdb{k}.conv{j}")
                        for j in range(1, 6)}
            for k in range(1, 4)
        }
    return tree_to_jnp(p), cfg


def load_upscale_checkpoint(src: Any, name: str = "upscaler") -> UpscaleModel:
    """Upscaler safetensors (either public layout) → UpscaleModel."""
    from .loader import _resolve_state_dict

    params, cfg = convert_upscale_checkpoint(_resolve_state_dict(src))
    return build_upscaler(cfg, params=params, name=name)


def upscale_image(model: UpscaleModel, image, tile: int = 512,
                  overlap: int = 16):
    """Upscale an NHWC [0,1] image batch; images larger than ``tile`` process
    as overlapping tiles blended with linear ramps (bounded activation memory
    at any resolution, no visible seams — the host's tiled upscale shape)."""
    img = jnp.asarray(image)
    if img.ndim == 3:
        img = img[None]
    B, H, W, C = img.shape
    s = model.cfg.scale
    if max(H, W) <= tile:
        return model(img)
    step = tile - 2 * overlap
    # Host-side numpy accumulators: a device .at[].add would copy the whole
    # full-resolution frame twice per tile — exactly the unbounded memory
    # traffic tiling exists to avoid. Only the per-tile model call runs on
    # device; each blended piece lands in place on the host.
    out = np.zeros((B, H * s, W * s, model.cfg.out_channels), np.float32)
    weight = np.zeros((1, H * s, W * s, 1), np.float32)

    def ramp(n, lo_edge, hi_edge):
        r = np.ones((n,), np.float32)
        k = overlap * s
        if lo_edge:
            r[:k] = np.linspace(0.0, 1.0, k)
        if hi_edge:
            r[-k:] = np.minimum(r[-k:], np.linspace(1.0, 0.0, k))
        return r

    ys = list(range(0, max(H - 2 * overlap, 1), step))
    xs = list(range(0, max(W - 2 * overlap, 1), step))
    for y0 in ys:
        y1 = min(y0 + tile, H)
        y0 = max(0, y1 - tile)
        for x0 in xs:
            x1 = min(x0 + tile, W)
            x0 = max(0, x1 - tile)
            piece = np.asarray(model(img[:, y0:y1, x0:x1, :]), np.float32)
            wy = ramp(piece.shape[1], y0 > 0, y1 < H)
            wx = ramp(piece.shape[2], x0 > 0, x1 < W)
            wgt = (wy[:, None] * wx[None, :])[None, :, :, None]
            out[:, y0 * s:y1 * s, x0 * s:x1 * s, :] += piece * wgt
            weight[:, y0 * s:y1 * s, x0 * s:x1 * s, :] += wgt
    return jnp.asarray(out / np.maximum(weight, 1e-8))
