"""Text encoders (CLIP-L / OpenCLIP-G / T5) — flax.linen, TPU-first.

The reference receives ready-made conditioning tensors from its host app (its
forward convention is ``forward(x, timesteps, context, **kwargs)`` with ``context``
already encoded, any_device_parallel.py:1287); standalone, this framework encodes
prompts itself. These are fresh implementations of the three encoder families the
supported checkpoints condition on:

- **CLIP-L** (SD1.5 context; SDXL & FLUX pooled vector): 12-layer pre-LN causal
  transformer, quick-gelu, 77-token window.
- **OpenCLIP-G** (SDXL context + pooled): 32-layer, gelu, penultimate-layer output.
- **T5 encoder** (FLUX/WAN context): RMSNorm, relative-position-bucket attention
  bias, gated-gelu FFN, bidirectional.

All take int32 token ids — tokenization is in utils/tokenizer.py (BPE/unigram
tables load from user-supplied files; this image ships none and has no egress).
Sequence lengths are static per call site (77 / 256 / 512), so every encode is a
single fixed-shape XLA program; attention masks are additive f32 biases fused into
the softmax, and matmuls run in the config compute dtype (bf16 on TPU) with f32
softmax/normalization.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# CLIP text towers
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_len: int = 77
    intermediate_size: int | None = None  # default 4*hidden
    act: str = "quick_gelu"  # "quick_gelu" (CLIP-L) | "gelu" (OpenCLIP-G)
    eos_id: int = 49407
    projection_dim: int | None = None  # text_projection for pooled (OpenCLIP / SDXL)
    # SD2's FrozenOpenCLIPEmbedder applies ln_final to the penultimate stream;
    # SDXL consumes it raw. Config-carried so consumers need no side channel.
    penultimate_ln: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def d_ff(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size


def clip_l_config(**overrides) -> CLIPTextConfig:
    """OpenAI CLIP ViT-L/14 text tower (SD1.5 context encoder; SDXL/FLUX 'clip_l')."""
    return dataclasses.replace(CLIPTextConfig(), **overrides)


def open_clip_h_config(**overrides) -> CLIPTextConfig:
    """OpenCLIP ViT-H/14 text tower (SD2.x context encoder): 1024 wide, 24
    layers, plain gelu; SD2.x conditions on the penultimate layer."""
    base = CLIPTextConfig(
        hidden_size=1024, num_layers=24, num_heads=16, act="gelu",
        projection_dim=1024, penultimate_ln=True,
    )
    return dataclasses.replace(base, **overrides)


def open_clip_g_config(**overrides) -> CLIPTextConfig:
    """OpenCLIP bigG/14 text tower (SDXL's second encoder)."""
    base = CLIPTextConfig(
        hidden_size=1280,
        num_layers=32,
        num_heads=20,
        act="gelu",
        projection_dim=1280,
    )
    return dataclasses.replace(base, **overrides)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * nn.sigmoid(1.702 * x)
    if name == "gelu":
        return lambda x: nn.gelu(x, approximate=False)  # HF/OpenCLIP "gelu" is exact erf
    raise ValueError(f"unknown activation {name!r}")


class _CLIPBlock(nn.Module):
    cfg: CLIPTextConfig

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.cfg
        H = cfg.num_heads
        D = cfg.hidden_size // H
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln1")(x)
        qkv = {
            n: nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name=n)(h) for n in "qkv"
        }
        B, S, _ = h.shape
        q, k, v = (qkv[n].reshape(B, S, H, D) for n in "qkv")
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D**-0.5)
        probs = jax.nn.softmax(logits.astype(jnp.float32) + bias, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        x = x + nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="out")(
            attn.reshape(B, S, cfg.hidden_size)
        )
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="fc1")(h)
        h = _act(self.cfg.act)(h)
        return x + nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="fc2")(h)


class CLIPTextModel(nn.Module):
    """Returns (last_hidden, penultimate_hidden, pooled). ``last_hidden`` has the
    final LayerNorm applied; ``penultimate_hidden`` is the raw layer-(N-1) stream
    (SDXL consumes exactly that, un-normed) unless ``cfg.penultimate_ln`` (SD2's
    OpenCLIP-H convention: ln_final applied). ``pooled`` reads the first-EOS
    position of the final-LN stream, projected when cfg.projection_dim is set."""

    cfg: CLIPTextConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        B, S = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok_emb")(
            tokens
        )
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.01), (cfg.max_len, cfg.hidden_size)
        )
        x = x + pos[None, :S].astype(cfg.dtype)
        causal = jnp.where(
            jnp.tril(jnp.ones((S, S), bool)), 0.0, -jnp.inf
        ).astype(jnp.float32)[None, None]
        penultimate = None
        for i in range(cfg.num_layers):
            if i == cfg.num_layers - 1:
                penultimate = x
            x = _CLIPBlock(cfg, name=f"layers_{i}")(x, causal)
        final_ln = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="final_ln")
        last = final_ln(x)
        if cfg.penultimate_ln:
            penultimate = final_ln(penultimate)
        eos_pos = jnp.argmax((tokens == cfg.eos_id).astype(jnp.int32), axis=-1)
        pooled = jnp.take_along_axis(last, eos_pos[:, None, None], axis=1)[:, 0]
        if cfg.projection_dim is not None:
            pooled = nn.Dense(
                cfg.projection_dim, use_bias=False, dtype=cfg.dtype, name="text_proj"
            )(pooled)
        return last, penultimate, pooled


# ---------------------------------------------------------------------------
# T5 encoder
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 4096
    num_layers: int = 24
    num_heads: int = 64
    d_kv: int = 64
    d_ff: int = 10240
    relative_buckets: int = 32
    relative_max_distance: int = 128
    # UMT5 gives every layer its own relative-position bias table; classic T5
    # shares layer 0's.
    per_layer_bias: bool = False
    dtype: Any = jnp.bfloat16


def t5_xxl_config(**overrides) -> T5Config:
    """google/t5-v1_1-xxl encoder — the FLUX 't5xxl' conditioning tower."""
    return dataclasses.replace(T5Config(), **overrides)


def umt5_xxl_config(**overrides) -> T5Config:
    """google/umt5-xxl encoder — the WAN conditioning tower (multilingual
    256k-token vocab, per-layer relative bias; otherwise the XXL geometry)."""
    base = T5Config(vocab_size=256384, per_layer_bias=True)
    return dataclasses.replace(base, **overrides)


def _t5_relative_buckets(rel_pos, num_buckets: int, max_distance: int):
    """Bidirectional T5 bucket scheme: sign split, then exact small distances,
    log-spaced large ones."""
    num_buckets //= 2
    ret = jnp.where(rel_pos > 0, num_buckets, 0)
    n = jnp.abs(rel_pos)
    max_exact = num_buckets // 2
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(n < max_exact, n, large)


class _T5RMSNorm(nn.Module):
    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


class _T5Block(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.d_kv
        inner = H * D
        h = _T5RMSNorm(name="ln1")(x)
        q = nn.Dense(inner, use_bias=False, dtype=cfg.dtype, name="q")(h)
        k = nn.Dense(inner, use_bias=False, dtype=cfg.dtype, name="k")(h)
        v = nn.Dense(inner, use_bias=False, dtype=cfg.dtype, name="v")(h)
        B, S, _ = h.shape
        q, k, v = (t.reshape(B, S, H, D) for t in (q, k, v))
        # T5 uses unscaled dot products (the 1/sqrt(d) is folded into init).
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) + bias
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, inner)
        x = x + nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="o")(attn)
        h = _T5RMSNorm(name="ln2")(x)
        wi0 = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="wi_0")(h)
        wi1 = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="wi_1")(h)
        h = nn.gelu(wi0, approximate=True) * wi1
        return x + nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype, name="wo")(h)


class T5Encoder(nn.Module):
    """Bidirectional T5 v1.1 / UMT5 encoder stack; returns the final RMS-normed
    stream. The relative-position bias table lives on layer 0 and is shared by
    all layers (T5 convention) unless ``cfg.per_layer_bias`` (UMT5: one table
    per layer); ``mask`` (B, S) of 0/1 marks real tokens."""

    cfg: T5Config

    @nn.compact
    def __call__(self, tokens, mask=None):
        cfg = self.cfg
        B, S = tokens.shape
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype, name="tok_emb")(
            tokens
        )
        pos = jnp.arange(S)
        buckets = _t5_relative_buckets(
            pos[None, :] - pos[:, None],
            cfg.relative_buckets,
            cfg.relative_max_distance,
        )
        mask_bias = 0.0
        if mask is not None:
            mask_bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -jnp.inf)

        def layer_bias(name: str):
            table = self.param(
                name,
                nn.initializers.normal(1.0),
                (cfg.relative_buckets, cfg.num_heads),
            )
            return table[buckets].transpose(2, 0, 1)[None].astype(jnp.float32) + mask_bias

        bias = None if cfg.per_layer_bias else layer_bias("rel_bias")
        for i in range(cfg.num_layers):
            b = layer_bias(f"rel_bias_{i}") if cfg.per_layer_bias else bias
            x = _T5Block(cfg, name=f"blocks_{i}")(x, b)
        return _T5RMSNorm(name="final_ln")(x)


# ---------------------------------------------------------------------------
# Builders (mirror build_flux/build_unet: params= skips init)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TextEncoder:
    """Encoder as data: jit-cached apply + weights (same shape as DiffusionModel)."""

    module: Any
    cfg: Any
    params: Any

    def _jitted(self):
        if not hasattr(self, "_jit_cache"):
            fn = jax.jit(
                lambda p, *a, **kw: self.module.apply({"params": p}, *a, **kw)
            )
            object.__setattr__(self, "_jit_cache", fn)
        return self._jit_cache

    def __call__(self, tokens, **kw):
        return self._jitted()(self.params, tokens, **kw)


def build_clip_text(cfg: CLIPTextConfig, rng=None, params=None) -> TextEncoder:
    module = CLIPTextModel(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        params = module.init(rng, jnp.zeros((1, cfg.max_len), jnp.int32))["params"]
    return TextEncoder(module=module, cfg=cfg, params=params)


def build_t5_encoder(cfg: T5Config, rng=None, params=None, sample_len=64) -> TextEncoder:
    module = T5Encoder(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        params = module.init(rng, jnp.zeros((1, sample_len), jnp.int32))["params"]
    return TextEncoder(module=module, cfg=cfg, params=params)


def sdxl_text_conditioning(
    l_penultimate, g_penultimate, g_pooled, width: int, height: int,
    crop_x: int = 0, crop_y: int = 0, target_width: int | None = None,
    target_height: int | None = None,
):
    """Assemble SDXL's (context, y) pair: context = CLIP-L ⊕ OpenCLIP-G penultimate
    streams (…, 768+1280=2048); y = G pooled (1280) ⊕ six sinusoidal size/crop
    embeddings (256 each → 2816 = the UNet's adm_in_channels)."""
    from ..ops.basic import timestep_embedding

    context = jnp.concatenate(
        [l_penultimate.astype(jnp.float32), g_penultimate.astype(jnp.float32)], axis=-1
    )
    B = g_pooled.shape[0]
    sizes = [
        height, width, crop_y, crop_x,
        target_height or height, target_width or width,
    ]
    embs = [
        timestep_embedding(jnp.full((B,), float(s), jnp.float32), 256) for s in sizes
    ]
    y = jnp.concatenate([g_pooled.astype(jnp.float32)] + embs, axis=-1)
    return context, y


def sdxl_refiner_text_conditioning(g_penultimate, g_pooled, width: int,
                                   height: int, ascore: float,
                                   crop_x: int = 0, crop_y: int = 0):
    """Assemble the SDXL-REFINER (context, y) pair: context = the OpenCLIP-G
    penultimate stream alone (1280-wide — the refiner has no CLIP-L tower);
    y = G pooled (1280) ⊕ five sinusoidal embeddings (256 each) in the
    refiner embedder's order — height, width, crop_y, crop_x, aesthetic
    score — totalling 2560 = the refiner UNet's adm_in_channels."""
    from ..ops.basic import timestep_embedding

    context = g_penultimate.astype(jnp.float32)
    B = g_pooled.shape[0]
    vals = [height, width, crop_y, crop_x, ascore]
    embs = [
        timestep_embedding(jnp.full((B,), float(v), jnp.float32), 256)
        for v in vals
    ]
    y = jnp.concatenate([g_pooled.astype(jnp.float32)] + embs, axis=-1)
    return context, y


def sd3_text_conditioning(l_penultimate, g_penultimate, l_pooled, g_pooled,
                          t5_context=None, context_dim: int = 4096):
    """Assemble SD3's (context, y): the CLIP joint stream (L ⊕ G penultimate,
    768+1280) zero-padded to ``context_dim`` and concatenated along the SEQUENCE
    axis with the T5 stream; y = L pooled ⊕ G pooled (2048)."""
    clip_joint = jnp.concatenate(
        [l_penultimate.astype(jnp.float32), g_penultimate.astype(jnp.float32)],
        axis=-1,
    )
    pad = context_dim - clip_joint.shape[-1]
    if pad < 0:
        raise ValueError(
            f"CLIP joint width {clip_joint.shape[-1]} exceeds {context_dim}"
        )
    clip_joint = jnp.pad(clip_joint, ((0, 0), (0, 0), (0, pad)))
    context = (
        jnp.concatenate([clip_joint, t5_context.astype(jnp.float32)], axis=1)
        if t5_context is not None
        else clip_joint
    )
    y = jnp.concatenate(
        [l_pooled.astype(jnp.float32), g_pooled.astype(jnp.float32)], axis=-1
    )
    return context, y
