"""Wrap-anything genericity: pipeline specs for models the framework has never
seen.

The reference wraps *any* torch module and auto-discovers its pipeline block
lists by name — ``['double_blocks', 'single_blocks', 'transformer_blocks',
'layers']`` (any_device_parallel.py:1156) — falling back to plain data
parallelism when none is found (1156-1166). The in-repo model zoo declares
hand-written ``PipelineSpec``s; this module closes the gap for third-party
models:

- ``derive_pipeline_spec(module, params)`` — auto-derive a spec from any flax
  module following the reference's naming convention: block submodule lists
  under one of the four names (setup-style, so params carry ``{name}_{i}``
  keys), plus ``prepare(x, t, context=None, **kw) -> carry`` and
  ``finalize(carry, out_shape)`` methods (the reference's non-block layers,
  which always run on the lead device, SURVEY §3.4).
- ``wrap_flax_module(module, params)`` — one call from a bare flax module to a
  ``DiffusionModel`` the orchestrator accepts, spec auto-derived when possible.
- ``parallelize(..., pipeline_spec=...)`` — the explicit hint for ``(apply,
  params)`` tuples that cannot carry attributes (orchestrator.py).
"""

from __future__ import annotations

import re
from typing import Any

from .api import DiffusionModel, PipelineSegment, PipelineSpec

# The reference's discovery list, in its walk order (1156).
BLOCK_LIST_NAMES = ("double_blocks", "single_blocks", "transformer_blocks", "layers")


def _block_groups(params) -> list[tuple[str, int]]:
    """(list_name, count) for every reference-named block list present as
    contiguous ``{name}_{i}`` keys in the top-level param pytree."""
    if not isinstance(params, dict):
        return []
    groups = []
    for name in BLOCK_LIST_NAMES:
        pat = re.compile(rf"^{re.escape(name)}_(\d+)$")
        idx = sorted(int(m.group(1)) for k in params if (m := pat.match(str(k))))
        if idx and idx == list(range(len(idx))):
            groups.append((name, len(idx)))
    return groups


def _call_block(m, carry, list_name: str, i: int):
    return getattr(m, list_name)[i](carry)


def derive_pipeline_spec(module, params) -> PipelineSpec | None:
    """Auto-derive a batch==1 pipeline decomposition, or None when the module
    doesn't follow the convention (the model still data-parallelizes — the
    reference's own fallback when no known block list is found, 1156-1166).

    Convention: ``module`` is a flax module whose forward is
    ``prepare → blocks (carry → carry, each) → finalize``, with the block lists
    defined in ``setup`` under a reference name so their params appear as
    ``{name}_{i}`` top-level keys."""
    if not (
        callable(getattr(module, "apply", None))
        and callable(getattr(type(module), "prepare", None))
        and callable(getattr(type(module), "finalize", None))
    ):
        return None
    if isinstance(params, dict) and set(params) == {"params"}:
        params = params["params"]
    groups = _block_groups(params)
    if not groups:
        return None

    mcls = type(module)

    def prepare(p, x, t, context=None, **kw):
        return module.apply({"params": p}, x, t, context, method=mcls.prepare, **kw)

    def make_seg(name: str, i: int):
        def fn(p, carry):
            return module.apply({"params": p}, carry, name, i, method=_call_block)

        return fn

    def finalize(p, carry, out_shape):
        return module.apply({"params": p}, carry, out_shape, method=mcls.finalize)

    segments = tuple(
        PipelineSegment((f"{name}_{i}",), make_seg(name, i), f"{name}[{i}]")
        for name, count in groups
        for i in range(count)
    )
    block_keys = {f"{name}_{i}" for name, count in groups for i in range(count)}
    # prepare/finalize both run on the lead device; the non-block remainder of
    # the pytree serves both (same device — placement dedups to one copy).
    rest = tuple(k for k in params if k not in block_keys)
    return PipelineSpec(
        prepare_keys=rest,
        prepare=prepare,
        segments=segments,
        finalize_keys=rest,
        finalize=finalize,
    )


def wrap_flax_module(
    module,
    params,
    name: str = "model",
    config: Any = None,
) -> DiffusionModel:
    """One call from a third-party flax module + params to an orchestrator-ready
    ``DiffusionModel``: the diffusion-forward convention
    ``__call__(x, timesteps, context=None, **kwargs)`` (the signature the
    reference's injected forward assumes, any_device_parallel.py:1287) becomes
    the pure apply; the batch==1 pipeline spec is auto-derived when the module
    follows the block-list convention, else None (data parallel only)."""
    if isinstance(params, dict) and set(params) == {"params"}:
        params = params["params"]

    def apply_fn(p, x, t, context=None, **kw):
        return module.apply({"params": p}, x, t, context, **kw)

    spec = derive_pipeline_spec(module, params)
    return DiffusionModel(
        apply=apply_fn,
        params=params,
        name=name,
        config=config,
        block_lists=dict(_block_groups(params)) or None,
        pipeline_spec=spec,
    )
