"""Shared tile-placement and overlap-blend math for the tiled VAE decoders.

Both the image VAE (models/vae.py) and the video VAE (models/video_vae.py) bound
decoder activation memory by decoding fixed-size overlapping latent tiles and
linearly blending the overlaps on the host; the per-axis start/mask arithmetic
lives here once so the two decoders cannot drift."""

from __future__ import annotations

import numpy as np


def tile_starts(size: int, tile: int, stride: int) -> list[int]:
    """Window starts covering ``size`` with ``tile``-long windows every
    ``stride``; the last window slides back inside the extent (never pads)."""
    if size <= tile:
        return [0]
    s = list(range(0, size - tile, stride))
    s.append(size - tile)
    return s


def blend_mask1d(tile: int, overlap: int, factor: int) -> np.ndarray:
    """Per-pixel blend weight along one axis for a decoded tile of ``tile``
    latent cells upsampled by ``factor``: a linear ramp over the overlap region
    at both ends, flat 1.0 in the interior."""
    if overlap == 0:
        return np.ones(tile * factor, np.float32)
    ramp = np.minimum(np.arange(tile * factor) + 1, overlap * factor) / (
        overlap * factor
    )
    return np.minimum(ramp, ramp[::-1]).astype(np.float32)
