"""Mixture-of-Experts FFN with expert-parallel sharding.

Absent in the reference (no MoE support, SURVEY §2e "Expert parallel: ❌") but part
of this framework's sharding vocabulary: diffusion transformers are adopting MoE FFNs
(e.g. WAN 2.2's high/low-noise expert split), and the mesh abstraction must carry the
``ep`` dimension.

Design (TPU-first, switch-style top-1 routing):

- **Dense dispatch**: every token computes against every *local* expert and a one-hot
  routing mask selects the winner — no gather/scatter, no capacity overflow, fully
  static shapes (XLA-friendly; the sparse all_to_all formulation only wins at large
  expert counts).
- **Expert parallelism** = sharding the expert dimension of the weight tensors over a
  mesh axis (``expert_sharding``); the XLA partitioner then runs each device's
  experts locally and all-reduces the mask-combined output — the einsum contraction
  over the expert axis becomes the combine collective.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MoEFFN(nn.Module):
    """Switch-style top-1 MoE FFN on (B, S, D) tokens.

    Router in f32; experts in compute dtype. Output = router_prob · expert_out
    (the switch scaling that keeps the router trainable/calibrated).
    """

    n_experts: int
    d_ff: int
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        B, S, D = x.shape
        E, F = self.n_experts, self.d_ff
        gate = self.param("gate", nn.initializers.lecun_normal(), (D, E))
        w_in = self.param(
            "w_in", nn.initializers.lecun_normal(batch_axis=(0,)), (E, D, F)
        )
        b_in = self.param("b_in", nn.initializers.zeros, (E, F))
        w_out = self.param(
            "w_out", nn.initializers.lecun_normal(batch_axis=(0,)), (E, F, D)
        )
        b_out = self.param("b_out", nn.initializers.zeros, (E, D))

        logits = x.astype(jnp.float32) @ gate.astype(jnp.float32)  # (B, S, E)
        probs = jax.nn.softmax(logits, axis=-1)
        top = jnp.argmax(probs, axis=-1)  # (B, S)
        mask = jax.nn.one_hot(top, E, dtype=jnp.float32)  # (B, S, E)
        combine = (mask * probs).astype(x.dtype)  # top-1 prob at the winner

        xc = x.astype(self.dtype)
        h = jnp.einsum("bsd,edf->bsef", xc, w_in.astype(self.dtype))
        h = nn.gelu(h + b_in.astype(self.dtype)[None, None])
        y = jnp.einsum("bsef,efd->bsed", h, w_out.astype(self.dtype))
        y = y + b_out.astype(self.dtype)[None, None]
        # Mask-combine over the expert axis — under EP sharding this contraction is
        # the combine all-reduce.
        return jnp.einsum("bsed,bse->bsd", y, combine).astype(x.dtype)


def expert_sharding(params, mesh: Mesh, axis: str = "model"):
    """Place MoEFFN params expert-parallel: expert-batched tensors (leading dim E)
    shard over ``axis``; the router gate replicates."""
    n = mesh.shape[axis]

    def put(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w_in", "b_in", "w_out", "b_out") and leaf.shape[0] % n == 0:
            spec = P(axis, *([None] * (leaf.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(put, params)
