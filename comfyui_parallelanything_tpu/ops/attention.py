"""Attention with pluggable backends.

The reference toggles flash/xformers OFF for old GPUs (disable_flash_xformers,
any_device_parallel.py:126-164) — capability-gated attention backends are part of its
surface. The TPU equivalent is a backend registry:

- ``"xla"``    — plain jnp dot-product attention; XLA fuses it well for moderate
  sequence lengths and it runs everywhere (the safe fallback, like the reference's
  post-disable path).
- ``"pallas"`` — fused flash-attention kernel for TPU (ops/pallas/), used for the long
  sequences of the FLUX/video configs.
- ``"auto"``   — pallas on TPU when available and the shape qualifies, else xla.

All functions take (B, S, H, D)-shaped q/k/v ("BSHD") and return (B, S, H, D).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BACKEND = "auto"


def set_attention_backend(name: str) -> None:
    global _BACKEND
    if name not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown attention backend {name!r}")
    _BACKEND = name


def get_attention_backend() -> str:
    return _BACKEND


def _xla_attention(q, k, v, scale):
    # (B, S, H, D) -> einsum over D; stable softmax in f32.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.cache
def _pallas_available() -> bool:
    try:
        devs = jax.devices()
    except RuntimeError:
        return False
    return any(d.platform == "tpu" for d in devs)


def attention(q, k, v, scale: float | None = None) -> jnp.ndarray:
    """Scaled dot-product attention on (B, S, H, D) inputs."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    backend = _BACKEND
    if backend == "auto":
        # The pallas kernel wants lane-aligned head dims and TPU hardware.
        use_pallas = (
            _pallas_available() and q.shape[-1] % 128 == 0 and q.shape[1] % 128 == 0
            and k.shape[1] % 128 == 0
        )
        backend = "pallas" if use_pallas else "xla"
    if backend == "pallas":
        from .pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, scale=scale)
    return _xla_attention(q, k, v, scale)
