"""Attention with pluggable backends.

The reference toggles flash/xformers OFF for old GPUs (disable_flash_xformers,
any_device_parallel.py:126-164) — capability-gated attention backends are part of its
surface. The TPU equivalent is a backend registry:

- ``"xla"``    — jnp dot-product attention; XLA fuses it well for moderate
  sequence lengths and it runs everywhere (the safe fallback, like the reference's
  post-disable path). Shapes whose S×S logits would exceed ``_CHUNK_THRESHOLD``
  are automatically served by the chunked path below.
- ``"xla_chunked"`` — memory-bounded attention in plain XLA ops (lax.scan over
  query blocks; the S×S logits tensor never materializes). The only path that
  fits SD-class 1024² workloads on one chip: 40/64-dim UNet heads can never
  take the pallas kernel, and materializing logits there needs >100 GB.
- ``"pallas"`` — fused flash-attention kernel for TPU (ops/pallas/), used for the long
  sequences of the FLUX/video configs.
- ``"pallas_jax"`` — jax's own battle-tested TPU flash kernel
  (jax.experimental.pallas.ops.tpu.flash_attention) as an alternative fused
  candidate: round-3's only hardware data point for the in-repo kernel was a
  30-minute hang at 4.6k tokens, so the kernel sweep measures BOTH fused
  implementations and the tuning table routes ``auto`` to whichever one
  actually won (128-aligned head dims only — no padding logic upstream).
- ``"auto"``   — the measured-best fused kernel on TPU when available and the
  shape qualifies, else the xla family (plain or chunked by size).

All functions take (B, S, H, D)-shaped q/k/v ("BSHD") and return (B, S, H, D).

Long-context: inside a ``sequence_parallel(mesh, ...)`` context every ``attention``
call routes through the sequence-parallel program (ring / Ulysses over the ``seq``
mesh axis, parallel/sequence.py) — so every model family gets context parallelism
without touching model code (absent in the reference, SURVEY §5.7; first-class here).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp


def _initial_backend() -> str:
    """Startup backend from ``PA_TPU_ATTENTION_BACKEND``
    (auto/xla/xla_chunked/pallas).

    The env override exists so a *driving process* (watchdog, bench harness, a
    hosted workflow run) can force the safe XLA path for every child it spawns
    when the fused kernel fails a hardware probe — without touching code. An
    invalid value falls back to "auto" rather than erroring at import time.
    """
    name = os.environ.get("PA_TPU_ATTENTION_BACKEND", "auto")
    return name if name in _BACKEND_NAMES else "auto"


_BACKEND_NAMES = ("auto", "xla", "xla_chunked", "pallas", "pallas_jax")

_BACKEND = _initial_backend()

_SEQ_CTX = threading.local()


@contextlib.contextmanager
def sequence_parallel(mesh, axis: str = "seq", method: str = "ring"):
    """Route all ``attention`` calls in this context over the mesh's sequence axis.

    Usable around a jitted model forward; the sharded program inlines into the trace.
    Sequence lengths must divide the axis size (ring) and heads must divide it too
    for ``method="ulysses"``.
    """
    prev = getattr(_SEQ_CTX, "cfg", None)
    _SEQ_CTX.cfg = (mesh, axis, method)
    try:
        yield
    finally:
        _SEQ_CTX.cfg = prev


def sequence_ctx_key() -> tuple | None:
    """Hashable identity of the active sequence_parallel context — the ctx is read at
    trace time, so every jit cache keyed on a model forward must include this (or a
    program traced under one context would be silently reused under another)."""
    cfg = getattr(_SEQ_CTX, "cfg", None)
    if cfg is None:
        return None
    mesh, axis, method = cfg
    return (mesh, axis, method)


_RESOLVED: set[str] = set()


def resolved_backends() -> tuple[str, ...]:
    """Backends that have actually served ``attention_local`` calls in this
    process, resolved at trace time — "auto" never appears here. Evidence
    labeling for benchmarks (a bench line must say which kernel produced the
    number), not a control surface."""
    return tuple(sorted(_RESOLVED))


def set_attention_backend(name: str) -> None:
    global _BACKEND
    if name not in _BACKEND_NAMES:
        raise ValueError(f"unknown attention backend {name!r}")
    _BACKEND = name


def get_attention_backend() -> str:
    return _BACKEND


def _xla_attention(q, k, v, scale, logits_dtype=jnp.float32):
    # (B, S, H, D) -> einsum over D; stable softmax (jax.nn.softmax subtracts
    # the row max) in ``logits_dtype`` — f32 everywhere EXCEPT the chunked
    # scan under the measured chunk tuning (see _xla_chunked_attention): the
    # sweep only measures that path, so the bf16 knob must not leak into
    # other models' plain-XLA softmax.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(logits_dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# Above this many f32 logits elements (B*H*S_q*S_k; 2**27 ≈ 512 MB) the
# materializing XLA path is routed to the chunked one. SD-class UNets at 1024²
# (16k tokens, batch 16) would need 137 GB of logits — far past any HBM — and
# their 40/64-dim heads can never take the lane-aligned pallas kernel, so
# chunking is the only way those workloads fit a chip at all.
_CHUNK_THRESHOLD = 2**27

# Measured chunk tuning (the sd15_16 MFU-budget fixes, BASELINE.md): the
# watchdog's chunk sweep benches {threshold × softmax-dtype} combos on
# hardware and persists the winner here; env vars override per-process for
# the sweep itself. Read at trace time — bench children are fresh processes.
_CHUNK_TUNING_PATH = os.environ.get("PA_ATTN_CHUNK_TUNING") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "attn_chunk.json"
)


@functools.cache
def _chunk_tuning() -> dict:
    import json

    try:
        with open(_CHUNK_TUNING_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


# Degradation-ladder override (utils/degrade.py "attn-chunk-shrink" rung):
# divides the effective chunk threshold for the REST of the process — a
# serving dispatch that OOMed at lane width 1 sheds logits memory next. The
# floor keeps block_q useful (2^20 elements ≈ 4 MB of f32 logits per block).
_CHUNK_SHRINK = 1
_CHUNK_FLOOR = 2**20


def _chunk_threshold() -> int:
    env = os.environ.get("PA_ATTN_CHUNK_ELEMS")
    base = int(env) if env else int(
        _chunk_tuning().get("chunk_elems", _CHUNK_THRESHOLD)
    )
    # The floor bounds LADDER shrinks only — a configured value (env var /
    # measured tuning) below the floor is served verbatim: the sweep and
    # tests deliberately force tiny thresholds.
    return max(min(base, _CHUNK_FLOOR), base // _CHUNK_SHRINK)


def shrink_chunk_threshold() -> int | None:
    """Halve the effective chunked-attention threshold (the ladder's
    "attn-chunk-shrink" rung); returns the new threshold, or None when
    already at the floor (the rung is spent — callers move to the next one).
    Programs traced before the shrink keep their old blocks — the caller
    must rebuild (clear_compiled_loops) for the shrink to take effect."""
    global _CHUNK_SHRINK
    if _chunk_threshold() <= _CHUNK_FLOOR:
        return None
    _CHUNK_SHRINK *= 2
    return _chunk_threshold()


def reset_chunk_shrink() -> None:
    """Undo ladder shrinks (tests / operator reset after the pressure ends)."""
    global _CHUNK_SHRINK
    _CHUNK_SHRINK = 1


def _softmax_dtype():
    env = os.environ.get("PA_ATTN_BF16_SOFTMAX")
    if env is not None:
        return jnp.bfloat16 if env == "1" else jnp.float32
    return jnp.bfloat16 if _chunk_tuning().get("bf16_softmax") else jnp.float32


def chunk_config() -> dict:
    """The chunk settings serving this process (evidence labeling: a bench
    record must say which configuration produced the number). ``sources``
    attributes each value separately — one env var being set must not
    mislabel the other value's provenance."""
    def src(env_name: str, table_key: str) -> str:
        if os.environ.get(env_name) is not None:
            return "env"
        if table_key in _chunk_tuning():
            return _chunk_tuning().get("source", "measured")
        return "default"

    return {
        "chunk_elems": _chunk_threshold(),
        "bf16_softmax": _softmax_dtype() == jnp.bfloat16,
        # True while the degradation ladder's attn-chunk-shrink rung is in
        # effect — evidence labeling: a degraded process must not bank its
        # numbers as the configured chunk setting.
        "degraded": _CHUNK_SHRINK > 1,
        "sources": {
            "chunk_elems": src("PA_ATTN_CHUNK_ELEMS", "chunk_elems"),
            "bf16_softmax": src("PA_ATTN_BF16_SOFTMAX", "bf16_softmax"),
        },
    }

# Block size of jax's upstream TPU flash kernel
# (pallas.ops.tpu.flash_attention.BlockSizes.get_default — 128 on every axis in
# the pinned jaxlib). The upstream kernel asserts seq_len % block == 0 and has
# no padding, so routing to "pallas_jax" must gate on this.
_UPSTREAM_BLOCK = 128


def _xla_chunked_attention(q, k, v, scale):
    """Memory-bounded attention without a fused kernel: a ``lax.scan`` over
    query blocks, each computing an ordinary softmax against the full K/V — the
    (B, H, S_q, S_k) logits tensor never materializes, only
    (B, H, block_q, S_k) slices do. The flash kernel's memory story with plain
    XLA ops: works for any head dim and any platform, trading one fused pass
    for nq sequential block passes (each still an MXU-shaped matmul pair)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    per_row = B * H * Sk
    block_q = max(16, min(Sq, _chunk_threshold() // max(per_row, 1)) // 16 * 16)
    if block_q >= Sq:
        return _xla_attention(q, k, v, scale)
    nq = -(-Sq // block_q)
    pad = nq * block_q - Sq
    qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (nq, B, block_q, H, D): scan over leading block axis; padded query rows
    # attend normally and are sliced away after.
    qb = qp.reshape(B, nq, block_q, H, D).transpose(1, 0, 2, 3, 4)
    # The measured softmax dtype applies to THIS path only — it's what the
    # chunk sweep benches (the scan's per-block logits round-trips are the
    # sd15_16 MFU budget's dominant traffic); plain-XLA softmax stays f32.
    logits_dtype = _softmax_dtype()

    def body(_, qblk):
        return None, _xla_attention(qblk, k, v, scale, logits_dtype=logits_dtype)

    _, out = jax.lax.scan(body, None, qb)
    out = out.transpose(1, 0, 2, 3, 4).reshape(B, nq * block_q, H, D)
    return out[:, :Sq]


def _pallas_jax_attention(q, k, v, scale):
    """jax's upstream fused TPU flash kernel, adapted from this module's BSHD
    layout to its BHSD one. TPU-only (no interpret path is wired); head dim
    must be 128-aligned (the upstream kernel has no lane-padding logic). Block
    sizes are left to the upstream defaults — its own heuristics are part of
    what makes it the battle-tested candidate."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as jax_flash,
    )

    qt, kt, vt = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    out = jax_flash(qt, kt, vt, sm_scale=float(scale))
    return out.transpose(0, 2, 1, 3)


@functools.cache
def _pallas_available() -> bool:
    from ..devices.discovery import is_tpu_device

    try:
        devs = jax.devices()
    except RuntimeError:
        return False
    return any(is_tpu_device(d) for d in devs)


def attention_local(q, k, v, scale: float | None = None) -> jnp.ndarray:
    """Backend-dispatched attention WITHOUT sequence-parallel routing — the local
    compute kernel, also safe to call from inside a shard_map body (where re-entering
    the seq-parallel path would recurse)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    backend = _BACKEND
    logit_elems = q.shape[0] * q.shape[2] * q.shape[1] * k.shape[1]
    if backend == "auto":
        from .pallas.tuning import pallas_wins

        # The kernel itself pads any head dim to 128 lanes (exact; see
        # flash_attention), so eligibility is just TPU + block-divisible
        # sequence; the measured tuning table (ops/pallas/tuning.py) decides
        # whether the fused kernel actually beats the XLA family at this
        # (length, head-dim class) — non-aligned dims pay a padded FLOP tax
        # and default to XLA until a measurement proves the kernel wins.
        use_pallas = (
            _pallas_available() and q.shape[1] % 128 == 0
            and k.shape[1] % 128 == 0
            and pallas_wins(q.shape[1], q.shape[-1])
        )
        if use_pallas:
            from .pallas.tuning import fused_backend

            # Which fused implementation won the measurement at this shape
            # class (in-repo streamed-KV kernel vs jax's upstream one).
            backend = fused_backend(q.shape[1], q.shape[-1])
        else:
            backend = "xla"
    if backend == "pallas_jax" and (
        q.shape[-1] % 128 != 0
        or q.shape[1] % _UPSTREAM_BLOCK != 0
        or k.shape[1] % _UPSTREAM_BLOCK != 0
    ):
        # The upstream kernel has no lane padding and asserts seq_len %
        # block == 0 (BlockSizes.get_default blocks are _UPSTREAM_BLOCK; no
        # internal padding). A FORCED pallas_jax (the watchdog's
        # probe-failure fallback) on a 40/64-dim head or a non-block-aligned
        # sequence takes the safe XLA family rather than crashing at trace
        # time on a shape the sweep never measured.
        backend = "xla"
    if backend == "xla" and logit_elems > _chunk_threshold():
        # "xla" means the XLA family: shapes whose S×S logits would blow HBM
        # (pallas-ineligible 40/64-dim UNet heads at 1024², or a forced
        # non-pallas run) go through the chunked path instead of OOMing.
        backend = "xla_chunked"
    _RESOLVED.add(backend)
    if backend == "pallas":
        from .pallas.flash_attention import flash_attention
        from .pallas.tuning import best_blocks

        block_q, block_k = best_blocks(q.shape[1], q.shape[-1])
        return flash_attention(
            q, k, v, scale=scale, block_q=block_q, block_k=block_k
        )
    if backend == "pallas_jax":
        return _pallas_jax_attention(q, k, v, scale)
    if backend == "xla_chunked":
        return _xla_chunked_attention(q, k, v, scale)
    return _xla_attention(q, k, v, scale)


def backend_plan(seq_q: int, seq_k: int | None = None,
                 head_dim: int | None = None, batch: int = 1,
                 heads: int = 1) -> dict:
    """The ``attention_local`` routing ladder as a side-effect-free,
    inspectable decision — what the auto-parallel planner's attention axis
    reads (parallel/planner.py): which backend WOULD serve this shape, the
    chunk configuration it would run under, and the banked measurements
    (``ops/attn_chunk.json`` threshold sweep + ``ops/pallas/tuning.json``
    pallas-vs-xla wins) that decided it. Mirrors ``attention_local`` rule
    for rule so plan and execution agree by construction; a drift test pins
    the two against each other (tests/test_planner.py)."""
    from .pallas.tuning import fused_backend, kernel_tuning, pallas_wins

    seq_k = seq_q if seq_k is None else int(seq_k)
    logit_elems = int(batch) * int(heads) * int(seq_q) * int(seq_k)
    threshold = _chunk_threshold()
    candidates: list[dict] = []

    def cand(name, eligible, why, **extra):
        candidates.append(
            {"backend": name, "eligible": bool(eligible), "why": why, **extra}
        )

    tuning = kernel_tuning()
    nearest = None
    measured = [e for e in tuning["entries"]
                if e.get("pallas_ms") is not None
                or e.get("pallas_jax_ms") is not None]
    if measured:
        nearest = min(
            measured, key=lambda e: abs(int(e.get("seq", 0)) - int(seq_q))
        )
    fused_ok = (
        _pallas_available() and seq_q % 128 == 0 and seq_k % 128 == 0
        and pallas_wins(seq_q, head_dim)
    )
    cand(
        "pallas", fused_ok and fused_backend(seq_q, head_dim) == "pallas",
        "fused in-repo kernel (tuning table winner)" if fused_ok
        else "ineligible: not TPU / non-128-aligned seq / tuning says XLA",
        measured_ms=(nearest or {}).get("pallas_ms"),
    )
    cand(
        "pallas_jax",
        fused_ok and fused_backend(seq_q, head_dim) == "pallas_jax",
        "jax upstream fused kernel (tuning table winner)" if fused_ok
        else "ineligible: not TPU / non-aligned / tuning says XLA",
        measured_ms=(nearest or {}).get("pallas_jax_ms"),
    )
    cand(
        "xla", not fused_ok and logit_elems <= threshold,
        f"materializing logits fit ({logit_elems} <= {threshold} elems)"
        if logit_elems <= threshold
        else f"logits would materialize {logit_elems} > {threshold} elems",
        measured_ms=(nearest or {}).get("xla_ms"),
    )
    cand(
        "xla_chunked", not fused_ok and logit_elems > threshold,
        "memory-bounded scan over query blocks (logits exceed threshold)",
        measured_ms=None,
    )
    # The exact attention_local resolution order: configured pin first, the
    # auto ladder only for "auto", then the pallas_jax shape guard and the
    # xla→chunked size fallback — so a process-pinned backend plans the same
    # way it executes.
    backend = _BACKEND
    if backend == "auto":
        backend = fused_backend(seq_q, head_dim) if fused_ok else "xla"
    if backend == "pallas_jax" and (
        (head_dim is not None and head_dim % 128 != 0)
        or seq_q % _UPSTREAM_BLOCK != 0 or seq_k % _UPSTREAM_BLOCK != 0
    ):
        backend = "xla"
    if backend == "xla" and logit_elems > threshold:
        backend = "xla_chunked"
    cfg = chunk_config()
    return {
        "backend": backend,
        "configured": _BACKEND,
        "logit_elems": logit_elems,
        "chunk_elems": cfg["chunk_elems"],
        "bf16_softmax": cfg["bf16_softmax"],
        "sources": cfg["sources"],
        "tuning_source": tuning.get("source", "default"),
        "candidates": candidates,
    }


def attention(q, k, v, scale: float | None = None) -> jnp.ndarray:
    """Scaled dot-product attention on (B, S, H, D) inputs."""
    seq_cfg = getattr(_SEQ_CTX, "cfg", None)
    if seq_cfg is not None:
        if scale is None:
            scale = q.shape[-1] ** -0.5
        from ..parallel.sequence import sharded_attention_inline

        mesh, axis, method = seq_cfg
        return sharded_attention_inline(q, k, v, mesh, axis, method, scale)
    return attention_local(q, k, v, scale)
