"""Multi-axis rotary position embeddings (FLUX-style).

FLUX's MMDiT positions tokens with per-axis RoPE over (seq, h, w) id triples with
per-axis dims like (16, 56, 56) summing to the head dim — the reference's config
scraper lists ``axes_dim``/``theta`` among the FLUX ctor kwargs it must preserve when
replicating (any_device_parallel.py:286-296). Computed in f32, applied in compute dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def axis_rope_freqs(ids: jnp.ndarray, axes_dim: tuple[int, ...], theta: float = 10000.0):
    """cos/sin tables for multi-axis RoPE.

    ids: (B, S, n_axes) integer positions per token per axis.
    Returns (cos, sin), each (B, S, sum(axes_dim)//2) f32.
    """
    parts_cos, parts_sin = [], []
    for i, dim in enumerate(axes_dim):
        half = dim // 2
        freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
        angles = ids[..., i].astype(jnp.float32)[..., None] * freqs  # (B, S, half)
        parts_cos.append(jnp.cos(angles))
        parts_sin.append(jnp.sin(angles))
    return jnp.concatenate(parts_cos, axis=-1), jnp.concatenate(parts_sin, axis=-1)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate pairs: x is (B, S, H, D); cos/sin are (B, S, D//2).

    Interleaved-pair convention: (x_even, x_odd) rotated by the per-pair angle.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    x_pairs = xf.reshape(*xf.shape[:-1], -1, 2)
    x_even, x_odd = x_pairs[..., 0], x_pairs[..., 1]
    c = cos[:, :, None, :]  # broadcast over heads
    s = sin[:, :, None, :]
    out_even = x_even * c - x_odd * s
    out_odd = x_even * s + x_odd * c
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(xf.shape)
    return out.astype(orig_dtype)
