"""Shared primitive ops for the model zoo."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_normalize(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    """RMSNorm in f32 with a learned scale, returned in x's dtype — the q/k norm used
    by the MMDiT families (FLUX QKNorm, WAN self/cross q/k norm)."""
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (normed * scale).astype(x.dtype)


def modulate(x: jnp.ndarray, shift: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """adaLN modulation ``x·(1+scale)+shift`` computed in f32, returned in x's dtype."""
    xf = x.astype(jnp.float32)
    return (xf * (1.0 + scale) + shift).astype(x.dtype)


def timestep_embedding(
    t: jnp.ndarray, dim: int, max_period: float = 10000.0, time_factor: float = 1.0
) -> jnp.ndarray:
    """Sinusoidal timestep embedding, (B,) -> (B, dim).

    The classic DDPM/transformer embedding used by every model family in scope (the
    reference's models compute this inside their torch UNet/DiT; it lives once here).
    Computed in float32 for stability, cast by callers.
    """
    t = time_factor * jnp.asarray(t, jnp.float32)
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.concatenate([emb, jnp.zeros_like(emb[:, :1])], axis=-1)
    return emb


def progress_window_gate(
    t_vec: jnp.ndarray, start: float, end: float, ndim: int,
    flow_time: bool = False,
) -> jnp.ndarray:
    """Per-batch sampling-progress window gate in {0, 1}, shaped (B, 1, ...)
    to broadcast over a rank-``ndim`` batch tensor (rank-safe for video's 5D
    latents). Progress runs 0 → 1 over the denoise: flow time IS the noise
    level (progress = 1 − t); the eps/v families carry table timesteps
    (progress = 1 − t/999 — the stock percent-window linear-in-t
    approximation). Shared by ControlNet's start/end percents
    (models/controlnet.apply_control) and ConditioningSetTimestepRange
    (sampling/k_samplers.EpsDenoiser) so the two gates cannot drift."""
    t = t_vec.astype(jnp.float32)
    progress = 1.0 - (t if flow_time else t / 999.0)
    on = (progress >= float(start)) & (progress <= float(end))
    return on.astype(jnp.float32).reshape((-1,) + (1,) * (ndim - 1))
