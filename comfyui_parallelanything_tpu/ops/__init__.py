from .basic import timestep_embedding
from .attention import attention, set_attention_backend, get_attention_backend

__all__ = [
    "timestep_embedding",
    "attention",
    "set_attention_backend",
    "get_attention_backend",
]
