"""Fused flash attention for TPU (Pallas).

The hot op of every model in scope: FLUX at 1024² is ~4.6k tokens of joint attention,
video models far more. The reference rides torch's bundled flash/xformers kernels and
merely toggles them off on old GPUs (any_device_parallel.py:126-164); here the fused
path is a Pallas kernel tuned for the MXU/VMEM hierarchy:

- grid over (batch·heads, query blocks); each program keeps one q block in VMEM
- online-softmax accumulation over k blocks (f32 running max/sum — no S×S
  materialization, HBM traffic stays O(S·D))
- bf16 in, f32 accumulate, caller dtype out

Non-TPU backends run the same kernel in interpreter mode (tests) or should prefer the
plain XLA path (ops/attention.py handles the dispatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int, seq_k: int):
    q = q_ref[...].astype(jnp.float32) * scale
    block_q, head_dim = q.shape
    padded_k = k_ref.shape[0]
    nk = padded_k // block_k

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)
        # Mask out-of-range key columns (host pads seq_k up to block_k multiple).
        col = i * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < seq_k, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0,
        nk,
        body,
        (
            jnp.zeros((block_q, head_dim), jnp.float32),
            jnp.full((block_q, 1), -jnp.inf, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32),
        ),
    )
    o_ref[...] = (acc / l).astype(o_ref.dtype)


def _pad_to(x, axis: int, multiple: int):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    scale: float | None = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool | None = None,
):
    """Flash attention on (B, S, H, D) q/k/v; returns (B, S_q, H, D).

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same kernel is
    testable on the virtual CPU mesh.
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]

    # (B, S, H, D) -> (B·H, S, D)
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(batch * heads, x.shape[1], head_dim)

    q3, k3, v3 = fold(q), fold(k), fold(v)
    bq = min(block_q, max(seq_q, 8))
    bk = min(block_k, max(seq_k, 8))
    q3 = _pad_to(q3, 1, bq)
    k3 = _pad_to(k3, 1, bk)
    v3 = _pad_to(v3, 1, bk)
    padded_q, padded_k = q3.shape[1], k3.shape[1]

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, block_k=bk, seq_k=seq_k),
        grid=(batch * heads, padded_q // bq),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, padded_k, head_dim), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, padded_k, head_dim), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, head_dim), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, padded_q, head_dim), q.dtype),
        interpret=interpret,
    )(q3, k3, v3)

    out = out[:, :seq_q, :]
    return out.reshape(batch, heads, seq_q, head_dim).transpose(0, 2, 1, 3)
