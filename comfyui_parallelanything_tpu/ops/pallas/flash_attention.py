"""Fused flash attention for TPU (Pallas).

The hot op of every model in scope: FLUX at 1024² is ~4.6k tokens of joint attention,
video models far more. The reference rides torch's bundled flash/xformers kernels and
merely toggles them off on old GPUs (any_device_parallel.py:126-164); here the fused
path is a Pallas kernel tuned for the MXU/VMEM hierarchy:

- grid over (batch·heads, query blocks, key blocks) — K/V stream through VMEM one
  ``block_k`` tile at a time, so VMEM holds O(block_q + block_k), NOT O(seq_k).
  This is what lets the same kernel cover WAN-video sequence lengths (tens of
  thousands of tokens): at 32k keys the old whole-row layout needed ~16 MB of
  VMEM per program just for K/V; streamed tiles stay ~1-2 MB at any length.
- online-softmax state (f32 running max/sum/acc) lives in VMEM scratch and is
  carried across the key-block grid dimension (the innermost, sequential one);
  the output tile is written once, on the last key block. No S×S
  materialization — HBM traffic stays O(S·D).
- bf16 in, f32 accumulate, caller dtype out.

Non-TPU backends run the same kernel in interpreter mode (tests) or should prefer the
plain XLA path (ops/attention.py handles the dispatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax >= 0.6 renamed TPUCompilerParams -> CompilerParams; support both.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

# m/l scratch rows are stored broadcast across a full 128-wide lane dimension —
# (block_q, 1) arrays lower poorly on the TPU vector unit.
_LANES = 128


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, scale: float,
    block_k: int, seq_k: int,
):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[...].astype(jnp.float32) * scale
    k_blk = k_ref[...].astype(jnp.float32)
    v_blk = v_ref[...].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (block_q, block_k)
    # Mask out-of-range key columns (host pads seq_k up to a block_k multiple).
    col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < seq_k, s, -jnp.inf)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finish():
        o_ref[...] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)


def _pad_to(x, axis: int, multiple: int):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_q", "block_k", "interpret")
)
def flash_attention(
    q,
    k,
    v,
    scale: float | None = None,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool | None = None,
):
    """Flash attention on (B, S, H, D) q/k/v; returns (B, S_q, H, D).

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same kernel is
    testable on the virtual CPU mesh.
    """
    from ...devices.discovery import is_tpu_device

    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = not is_tpu_device(jax.devices()[0])

    # Lane alignment: the MXU wants the head dim in 128-lane multiples. For
    # the 40/64-dim UNet-family heads, zero-pad D — exact, not approximate:
    # padded K columns add zero to every q·k logit, and padded V columns
    # produce zeros that are sliced away below. (Scale was already fixed from
    # the ORIGINAL head dim above.) Whether the padded FLOP tax beats chunked
    # XLA at a given shape is a tuning-table question (ops/pallas/tuning.py);
    # this function just makes any head dim runnable.
    orig_head_dim = q.shape[-1]
    lane_pad = (-orig_head_dim) % 128
    if lane_pad:
        pad_spec = ((0, 0), (0, 0), (0, 0), (0, lane_pad))
        q = jnp.pad(q, pad_spec)
        k = jnp.pad(k, pad_spec)
        v = jnp.pad(v, pad_spec)

    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]

    # (B, S, H, D) -> (B·H, S, D)
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(batch * heads, x.shape[1], head_dim)

    q3, k3, v3 = fold(q), fold(k), fold(v)
    bq = min(block_q, max(seq_q, 8))
    bk = min(block_k, max(seq_k, 8))
    q3 = _pad_to(q3, 1, bq)
    k3 = _pad_to(k3, 1, bk)
    v3 = _pad_to(v3, 1, bk)
    padded_q, padded_k = q3.shape[1], k3.shape[1]

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, block_k=bk, seq_k=seq_k),
        # Key blocks are the innermost (sequential) grid dim: scratch carries the
        # online-softmax state across them, and the output tile (whose index map
        # ignores j) stays resident in VMEM until its last visit.
        grid=(batch * heads, padded_q // bq, padded_k // bk),
        in_specs=[
            pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, bk, head_dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, head_dim), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, padded_q, head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3)

    out = out[:, :seq_q, :]
    out = out.reshape(batch, heads, seq_q, head_dim).transpose(0, 2, 1, 3)
    if lane_pad:
        out = out[..., :orig_head_dim]
    return out
