"""Data-driven flash-kernel tuning (VERDICT r2 item 7).

The pallas kernel's block sizes (256/256) started as guesses; real numbers come
from ``scripts/bench_kernels.py``, which sweeps ``block_q``/``block_k`` over
{128, 256, 512} at the shapes that matter (FLUX 4.6k joint attention, WAN
16k/32k video) and — with ``--apply`` — writes the winners here as
``tuning.json``. The ``auto`` attention backend (ops/attention.py) then:

- picks the measured-best blocks for the nearest benchmarked sequence length,
- falls back to XLA for sequence ranges where the measurement says the fused
  kernel LOSES (the reference's capability-gated backend disable, inverted:
  data-gated instead of SM-version-gated, any_device_parallel.py:126-164).

Without a measured file everything behaves exactly as the defaults did.
"""

from __future__ import annotations

import functools
import json
import os

# PA_TUNING_PATH override exists for the watchdog dry-run (tests write a
# throwaway measured table without touching the packaged one).
_PATH = os.environ.get("PA_TUNING_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tuning.json"
)

_DEFAULT = {
    "source": "default",       # "measured" once bench_kernels --apply ran
    "device_kind": None,
    "block_q": 256,
    "block_k": 256,
    # [{"seq": int, "head_dim": int|None, "block_q": int, "block_k": int,
    #   "pallas_ms": float, "xla_ms": float|None}, ...]
    # head_dim tags a measurement to its dim class (non-128-aligned dims run
    # the kernel zero-padded and must win their own measurements).
    "entries": [],
}


@functools.lru_cache(maxsize=1)
def kernel_tuning() -> dict:
    """The active tuning table (defaults merged under any measured file).

    A measured table is generation-specific: block winners and win/lose ranges
    from a v5e do not transfer to a v6e. When the file records a
    ``device_kind`` that doesn't match the current first accelerator, fall back
    to defaults rather than silently applying foreign measurements."""
    try:
        with open(_PATH) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            raise ValueError("tuning.json must hold an object")
        measured_kind = data.get("device_kind")
        if measured_kind:
            try:
                import jax

                current = jax.devices()[0].device_kind
            except Exception:
                current = None
            if current is not None and current != measured_kind:
                return dict(_DEFAULT)
        return {**_DEFAULT, **data}
    except Exception:
        return dict(_DEFAULT)


def _nearest(entries: list, seq: int):
    return min(entries, key=lambda e: abs(int(e.get("seq", 0)) - seq))


def best_blocks(seq: int, head_dim: int | None = None) -> tuple[int, int]:
    """(block_q, block_k) for a sequence length: the measured winner at the
    nearest benchmarked length (preferring measurements of the same head-dim
    class), else the defaults."""
    t = kernel_tuning()
    entries = [e for e in t["entries"] if e.get("block_q") and e.get("block_k")]
    if head_dim is not None:
        same_dim = [e for e in entries if e.get("head_dim") == head_dim]
        if same_dim:
            entries = same_dim
        elif head_dim % 128 != 0:
            # Padded dim with no same-dim measurement: return the defaults
            # rather than inheriting blocks tuned for a different dim class —
            # mirrors pallas_wins' filtering, which matters when a forced
            # (non-auto) pallas backend runs a padded shape the sweep never
            # measured.
            entries = []
        else:
            # Aligned dims must not inherit blocks tuned under the padded-FLOP
            # regime of a different dim class (mirrors pallas_wins).
            entries = [
                e for e in entries
                if e.get("head_dim") is None or e["head_dim"] % 128 == 0
            ]
    if not entries:
        return int(t["block_q"]), int(t["block_k"])
    e = _nearest(entries, seq)
    return int(e["block_q"]), int(e["block_k"])


def _fused_ms(e: dict):
    """Best measured fused-kernel time for an entry: min over the in-repo
    kernel (``pallas_ms``) and jax's upstream one (``pallas_jax_ms``)."""
    times = [e.get("pallas_ms"), e.get("pallas_jax_ms")]
    times = [t for t in times if t is not None]
    return min(times) if times else None


def fused_backend(seq: int, head_dim: int | None = None) -> str:
    """Which fused implementation serves this shape class: "pallas_jax" when
    jax's upstream kernel measured faster at the nearest benchmarked length
    (and the dim is lane-aligned — upstream has no padding logic), else the
    in-repo "pallas"."""
    if head_dim is not None and head_dim % 128 != 0:
        return "pallas"
    t = kernel_tuning()
    entries = [e for e in t["entries"] if _fused_ms(e) is not None]
    if head_dim is not None:
        same_dim = [e for e in entries if e.get("head_dim") == head_dim]
        entries = same_dim or [
            e for e in entries
            if e.get("head_dim") is None or e.get("head_dim", 0) % 128 == 0
        ]
    if not entries:
        return "pallas"
    e = _nearest(entries, seq)
    pj, pm = e.get("pallas_jax_ms"), e.get("pallas_ms")
    if pj is not None and (pm is None or pj < pm):
        return "pallas_jax"
    return "pallas"


def pallas_wins(seq: int, head_dim: int | None = None) -> bool:
    """Whether the fused kernel beat XLA at the nearest measured length. With
    no measurement, True for lane-aligned head dims — the default guess (XLA's
    S×S logits materialization loses at the long lengths this path serves) —
    but False for non-aligned dims (40/64 UNet heads): those run the kernel
    zero-PADDED to 128 lanes, a 2-3.2× FLOP tax that must *prove* it beats the
    chunked-XLA path before auto picks it. Entries measured at a specific
    ``head_dim`` (bench_kernels records it) gate their own dim class; an entry
    whose XLA measurement FAILED (``xla_ms`` None — S×S logits OOM) counts as
    a pallas win: that is a length where the fused kernel is mandatory, not
    absent data."""
    t = kernel_tuning()
    entries = [e for e in t["entries"] if _fused_ms(e) is not None]
    padded_dim = head_dim is not None and head_dim % 128 != 0
    if head_dim is not None:
        same_dim = [e for e in entries if e.get("head_dim") == head_dim]
        if same_dim:
            entries = same_dim
        elif padded_dim:
            return False
        else:
            # Aligned dim: generic (dim-less or aligned-dim) entries apply.
            entries = [
                e for e in entries
                if e.get("head_dim") is None or e["head_dim"] % 128 == 0
            ]
    if not entries:
        return True
    e = _nearest(entries, seq)
    if padded_dim and not (seq / 2 <= int(e.get("seq", 0)) <= seq * 2):
        # A padded-dim win extrapolates at most 2x in sequence length: the
        # padded FLOP tax that wins at 16k against chunked XLA was never
        # measured against the cheap plain-XLA competitor at short lengths.
        return False
    if e.get("xla_ms") is None:
        return True
    return float(_fused_ms(e)) <= float(e["xla_ms"])


def write_tuning(data: dict) -> str:
    """Persist a measured tuning table (bench_kernels --apply) and reload."""
    merged = {**_DEFAULT, **data, "source": "measured"}
    with open(_PATH, "w") as f:
        json.dump(merged, f, indent=2, sort_keys=True)
        f.write("\n")
    kernel_tuning.cache_clear()
    return _PATH
