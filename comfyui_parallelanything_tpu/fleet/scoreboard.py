"""Per-host health scoreboard: admission state polled from ``GET /health``.

The router never guesses about a backend — every placement decision reads
this scoreboard, which in turn reads only the backends' existing health
surface (``pa-health/v3``, utils/telemetry.health_snapshot + the queue/host
fields server.py adds): queue depth, in-flight prompts, the drain flag, the
HBM watermark/utilization, compile-cache accounting, and the numerics-gate
verdict. No side channel, no extra endpoint — if the health document can't
see a problem, neither can an operator, and fixing THAT is the job.

Staleness-aware backoff: a host that fails a poll is retried on an
exponential backoff (so a dead host costs one socket timeout per backoff
interval, not per scheduling decision), and an entry whose last successful
poll is older than ``stale_after_s`` stops counting as healthy even if the
last document looked fine — admission decisions are only as good as their
data's age. ``fail_after`` consecutive failures mark the host DEAD, which is
the router's failover trigger.

Pure stdlib; nothing here imports jax.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request

from ..utils import faults
from ..utils import retry as retry_mod
from ..utils.logging import get_logger
from ..utils.metrics import registry

log = get_logger()


def _esc_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_host(line: str, host_id: str) -> str:
    """Inject ``host="<id>"`` into one exposition sample line. A line
    already carrying a host label keeps it (the router's own
    ``pa_fleet_host_*`` gauges name backends, not the router)."""
    head, _, value = line.rpartition(" ")
    if not head:
        return line
    if "{" in head:
        name, _, labels = head.partition("{")
        if 'host="' in labels:
            return line
        return f'{name}{{host="{_esc_label(host_id)}",{labels} {value}'
    return f'{head}{{host="{_esc_label(host_id)}"}} {value}'


def merge_metrics(texts: dict[str, str]) -> str:
    """Merge per-host Prometheus expositions into ONE host-labeled view
    (``GET /fleet/metrics``): every sample line gains a ``host`` label, and
    samples regroup under one ``# HELP``/``# TYPE`` block per metric family
    (exposition format requires a family's samples to be contiguous —
    interleaving N hosts' blocks verbatim would not parse). First host's
    HELP text wins; histogram ``_bucket``/``_sum``/``_count`` samples
    follow their family."""
    families: dict[str, dict] = {}
    order: list[str] = []

    def fam_slot(name: str) -> dict:
        f = families.get(name)
        if f is None:
            f = families[name] = {"help": None, "type": None, "samples": []}
            order.append(name)
        return f

    for hid, text in texts.items():
        local_types: dict[str, str] = {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) == 4:
                    local_types[parts[2]] = parts[3]
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) < 4:
                    continue
                f = fam_slot(parts[2])
                key = "help" if parts[1] == "HELP" else "type"
                if f[key] is None:
                    f[key] = parts[3]
                continue
            if line.startswith("#"):
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and base in local_types:
                    family = base
                    break
            fam_slot(family)["samples"].append(_label_host(line, hid))
    lines: list[str] = []
    for name in order:
        f = families[name]
        if not f["samples"]:
            continue
        if f["help"] is not None:
            lines.append(f"# HELP {name} {f['help']}")
        if f["type"] is not None:
            lines.append(f"# TYPE {name} {f['type']}")
        lines.extend(f["samples"])
    return "\n".join(lines) + "\n"


@dataclasses.dataclass
class HostHealth:
    """Last known health of one backend, plus the poll bookkeeping."""

    host_id: str
    base: str
    # -- from the health document (pa-health/v3; v2 fields unchanged) --
    accepting: bool = True
    inflight_prompts: int = 0
    queue_pending: int = 0
    queue_running: int = 0
    workers: int = 1
    hbm_utilization_max: float | None = None
    peak_hbm_bytes: int | None = None
    compile_cache: dict | None = None      # {compiles, cache_hits, cache_misses}
    numerics_ok: bool = True
    quarantined_lanes: int = 0             # surfaced, not an admission signal
    schema: str | None = None
    serving_batched_fraction: float | None = None
    # pa-health/v3: model keys the host serves warm (compiled programs /
    # pinned weights resident) — the residency-aware failover preference.
    warm_keys: frozenset = frozenset()
    # Role-pool membership the host's own /health advertises
    # (fleet/roles.py) — how statically configured --backends hosts, which
    # never heartbeat a role, still land in the right pool.
    role: str = "all"
    # -- poll bookkeeping (time.monotonic clocks) --
    last_ok: float | None = None
    consecutive_failures: int = 0
    next_poll: float = 0.0
    last_error: str | None = None
    # -- /metrics scrape cache (GET /fleet/metrics) --
    metrics_text: str | None = None
    metrics_ts: float | None = None
    # -- /metrics/history scrape cache (GET /fleet/history) --
    history_doc: dict | None = None
    history_ts: float | None = None

    def age_s(self, now: float | None = None) -> float | None:
        if self.last_ok is None:
            return None
        return (time.monotonic() if now is None else now) - self.last_ok


class Scoreboard:
    """Polls backend health into per-host entries and answers the router's
    three questions: is this host healthy, is it accepting, is it saturated.

    Thread-safe; ``poll_due`` is driven by the router's monitor thread, and
    ``record_failure`` lets the router's own proxy errors (a refused
    ``POST /prompt``) feed the same failure counter as a failed poll — a
    host that eats dispatches is as dead as one that fails health checks."""

    def __init__(self, poll_s: float = 1.0, stale_after_s: float = 10.0,
                 fail_after: int = 3, timeout_s: float = 5.0,
                 backoff_cap_s: float = 30.0,
                 retry_policy: retry_mod.RetryPolicy | None = None):
        self.poll_s = float(poll_s)
        self.stale_after_s = float(stale_after_s)
        self.fail_after = int(fail_after)
        self.timeout_s = float(timeout_s)
        self.backoff_cap_s = float(backoff_cap_s)
        # The shared retry shape (utils/retry.py): poll backoff after
        # failures doubles per failure toward the cap, with deterministic
        # per-host jitter so N backends' re-polls never synchronize.
        self.retry_policy = retry_policy or retry_mod.RetryPolicy(
            max_attempts=1_000_000, base_s=self.poll_s * 2,
            cap_s=self.backoff_cap_s, jitter=0.25,
        )
        self._entries: dict[str, HostHealth] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- polling ------------------------------------------------------------

    def _entry(self, host_id: str, base: str) -> HostHealth:  # palint: holds _lock
        e = self._entries.get(host_id)
        if e is None or e.base != base:
            e = self._entries[host_id] = HostHealth(host_id, base)
        return e

    def poll_due(self, hosts: dict[str, str]) -> list[str]:
        """Poll every host whose backoff window has elapsed; returns the
        host ids polled. ``hosts`` is {host_id: base} (the registry's view);
        entries for departed hosts are dropped."""
        now = time.monotonic()
        with self._lock:
            for hid in list(self._entries):
                if hid not in hosts:
                    del self._entries[hid]
            due = [
                (hid, base) for hid, base in hosts.items()
                if self._entry(hid, base).next_poll <= now
            ]
        for hid, base in due:
            self.poll_host(hid, base)
        return [hid for hid, _ in due]

    def poll_host(self, host_id: str, base: str) -> bool:
        """One ``GET /health`` poll; True on success. Never raises."""
        # Fault site (utils/faults.py ``network-partition``): health polls
        # are router→backend traffic too — a partitioned host must go dark
        # on the scoreboard exactly as it does on the dispatch path, or the
        # router would keep placing onto a host it can no longer reach.
        if faults.check("network-partition", key=f"router->{base}") is not None:
            self.record_failure(host_id, base, "injected network partition")
            return False
        try:
            with urllib.request.urlopen(
                base + "/health", timeout=self.timeout_s
            ) as r:
                doc = json.loads(r.read())
        except (OSError, ValueError) as e:
            self.record_failure(host_id, base, f"{type(e).__name__}: {e}")
            return False
        now = time.monotonic()
        queue = doc.get("queue") or {}
        numerics = doc.get("numerics") or {}
        gate = numerics.get("fingerprint_gate") or {}
        with self._lock:
            e = self._entry(host_id, base)
            e.schema = doc.get("schema")
            e.accepting = bool(doc.get("accepting", True))
            e.inflight_prompts = int(
                doc.get("inflight_prompts",
                        queue.get("pending", 0) + queue.get("running", 0))
            )
            e.queue_pending = int(queue.get("pending", 0))
            e.queue_running = int(queue.get("running", 0))
            e.workers = int(queue.get("workers", 1))
            e.serving_batched_fraction = queue.get("serving_batched_fraction")
            e.hbm_utilization_max = doc.get("hbm_utilization_max")
            e.peak_hbm_bytes = doc.get("peak_hbm_bytes")
            comp = doc.get("compile") or {}
            e.compile_cache = {
                k: comp.get(k)
                for k in ("compiles", "cache_hits", "cache_misses")
            }
            # The admission signal is the fingerprint GATE's verdict (a host
            # whose numbers drifted should get no new work) — NOT the
            # cumulative quarantine counter: a quarantine already failed its
            # own prompt at the lane, and one bad request in a process's
            # lifetime must not blacklist the host forever. The counter is
            # surfaced for operators instead.
            e.numerics_ok = gate.get("verdict") not in ("drift", "nonfinite")
            e.quarantined_lanes = int(numerics.get("quarantined_lanes") or 0)
            # pa-health/v3 residency: which model keys the host serves warm
            # (absent on v2 hosts → empty set — mixed-version fleets degrade
            # to the old cold-blind placement).
            e.warm_keys = frozenset(
                str(k) for k in (doc.get("warm_keys") or ())
            )
            e.role = str(doc.get("role") or "all")
            e.last_ok = now
            e.consecutive_failures = 0
            e.last_error = None
            e.next_poll = now + self.poll_s
        return True

    def record_failure(self, host_id: str, base: str | None = None,
                       error: str = "") -> int:
        """Register one failed interaction (poll or proxy); returns the new
        consecutive-failure count. Backoff doubles per failure, capped."""
        now = time.monotonic()
        with self._lock:
            e = self._entry(host_id, base or self._entries.get(
                host_id, HostHealth(host_id, "")
            ).base)
            e.consecutive_failures += 1
            e.last_error = error or e.last_error
            # Shared backoff shape (utils/retry.py): exponential toward the
            # cap with deterministic per-host jitter — a fleet of failing
            # hosts de-synchronizes instead of re-polling in lockstep.
            e.next_poll = now + self.retry_policy.backoff_s(
                min(e.consecutive_failures - 1, 8), key=host_id
            )
            n = e.consecutive_failures
        if n == self.fail_after:
            log.warning("fleet host %s marked dead after %d failures (%s)",
                        host_id, n, error)
        return n

    # -- metrics scrape (GET /fleet/metrics) --------------------------------

    def scrape_metrics(self, host_id: str, base: str) -> tuple[str | None, float | None]:
        """One host's ``GET /metrics`` body for the fleet-wide merged view,
        riding the health-poll failure bookkeeping: a host in failure
        backoff (or already dead) is NEVER re-fetched here — its cached
        text serves with a staleness marker instead, so one dead backend
        degrades the merged view by exactly its own staleness and never
        stalls the scrape past the poll timeout. Returns
        ``(text_or_None, age_s_or_None)``."""
        now = time.monotonic()
        with self._lock:
            e = self._entry(host_id, base)
            skip = (e.consecutive_failures >= self.fail_after
                    or (e.consecutive_failures > 0 and e.next_poll > now))
            cached, cached_ts = e.metrics_text, e.metrics_ts
        if not skip and cached_ts is not None and now - cached_ts < self.poll_s:
            # Freshness window: a scrape younger than the poll interval
            # serves from cache — back-to-back /fleet/metrics + /fleet/slo
            # (or an eager dashboard) must not double every backend's
            # /metrics load, and N sequential fetches must not stack
            # request latency on every view.
            return cached, now - cached_ts
        if skip:
            return cached, (now - cached_ts) if cached_ts is not None else None
        try:
            with urllib.request.urlopen(
                base + "/metrics", timeout=self.timeout_s
            ) as r:
                text = r.read().decode("utf-8", "replace")
        except (OSError, ValueError) as e:
            # The same failure counter as a failed health poll — a host
            # that eats metrics scrapes is as suspect as one that eats
            # health checks, and the shared backoff keeps the NEXT merged
            # view from paying this timeout again.
            self.record_failure(host_id, base, f"metrics: {e}")
            now = time.monotonic()
            return cached, (now - cached_ts) if cached_ts is not None else None
        now = time.monotonic()
        with self._lock:
            e = self._entry(host_id, base)
            e.metrics_text = text
            e.metrics_ts = now
        return text, 0.0

    # -- history scrape (GET /fleet/history) --------------------------------

    def scrape_history(self, host_id: str, base: str,
                       window_s: float | None = None,
                       ) -> tuple[dict | None, float | None]:
        """One host's ``GET /metrics/history`` window (pa-history/v1) for
        the fleet-merged view, riding the EXACT scrape_metrics discipline:
        a host in failure backoff or dead serves its cached window (the
        router's staleness marker tells the reader), a cache younger than
        the poll interval serves without re-fetching, and a failed fetch
        feeds the shared failure counter. Returns
        ``(doc_or_None, age_s_or_None)``."""
        now = time.monotonic()
        with self._lock:
            e = self._entry(host_id, base)
            skip = (e.consecutive_failures >= self.fail_after
                    or (e.consecutive_failures > 0 and e.next_poll > now))
            cached, cached_ts = e.history_doc, e.history_ts
        if not skip and cached_ts is not None and now - cached_ts < self.poll_s:
            return cached, now - cached_ts
        if skip:
            return cached, (now - cached_ts) if cached_ts is not None else None
        url = base + "/metrics/history"
        if window_s is not None:
            url += f"?window={float(window_s):g}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                doc = json.loads(r.read())
        except (OSError, ValueError) as e:
            self.record_failure(host_id, base, f"history: {e}")
            now = time.monotonic()
            return cached, (now - cached_ts) if cached_ts is not None else None
        now = time.monotonic()
        with self._lock:
            e = self._entry(host_id, base)
            e.history_doc = doc
            e.history_ts = now
        return doc, 0.0

    # -- the router's three questions ---------------------------------------

    def healthy(self, host_id: str, now: float | None = None) -> bool:
        """Fresh data, under the failure limit, numerics clean."""
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.get(host_id)
            if e is None or e.last_ok is None:
                return False
            if e.consecutive_failures >= self.fail_after:
                return False
            if now - e.last_ok > self.stale_after_s:
                return False
            return e.numerics_ok

    def accepting(self, host_id: str) -> bool:
        """Healthy AND not draining."""
        if not self.healthy(host_id):
            return False
        with self._lock:
            return self._entries[host_id].accepting

    def last_ok(self, host_id: str) -> float | None:
        """time.monotonic() of the host's last successful poll, or None."""
        with self._lock:
            e = self._entries.get(host_id)
            return e.last_ok if e is not None else None

    def role_of(self, host_id: str) -> str | None:
        """The role the host's own /health advertises, or None before the
        first successful poll — RolePools (fleet/roles.py) falls back to
        this when the registry has no heartbeat-declared role."""
        with self._lock:
            e = self._entries.get(host_id)
            if e is None or e.last_ok is None:
                return None
            return e.role

    def warm(self, host_id: str, key: str) -> bool:
        """Does the host advertise ``key`` in its warm set (pa-health/v3)?
        The router's failover re-dispatch prefers warm siblings over a cold
        primary — replaying a dead host's prompt on a host that must first
        stage the model costs compile + weight placement."""
        with self._lock:
            e = self._entries.get(host_id)
            return e is not None and key in e.warm_keys

    def saturated(self, host_id: str, extra_inflight: int = 0,
                  depth: int = 4,
                  hbm_watermark: float | None = 0.95,
                  include_polled: bool = True) -> bool:
        """At or beyond the per-host admission depth. ``extra_inflight`` is
        the router's own live dispatch count for the host; the polled
        document lags it (and, once fresh, COUNTS the same prompts), so the
        two views combine as max, not sum — and the caller passes
        ``include_polled=False`` when the poll predates its own bookkeeping
        (a completion the router already collected makes the polled count
        provably stale-high, which would strand a free host as "saturated"
        for a poll interval). HBM pressure beyond the watermark counts as
        saturation too — spilling beats OOMing a warm host."""
        with self._lock:
            e = self._entries.get(host_id)
            if e is None:
                return True
            inflight = max(e.inflight_prompts if include_polled else 0,
                           extra_inflight, 0)
            if inflight >= depth:
                return True
            if (hbm_watermark is not None
                    and e.hbm_utilization_max is not None
                    and e.hbm_utilization_max >= hbm_watermark):
                return True
            return False

    def dead(self, host_id: str) -> bool:
        with self._lock:
            e = self._entries.get(host_id)
            return (e is not None
                    and e.consecutive_failures >= self.fail_after)

    def in_backoff(self, host_id: str) -> bool:
        """True while the host has recorded failures and its backoff window
        has not elapsed — best-effort traffic (the monitor's history sweeps)
        should not pay a socket timeout per visit to a struggling host."""
        with self._lock:
            e = self._entries.get(host_id)
            return (e is not None and e.consecutive_failures > 0
                    and e.next_poll > time.monotonic())

    def mark_draining(self, host_id: str) -> None:
        """Immediate local effect of a drain request — the next poll will
        confirm from the host's own document."""
        with self._lock:
            e = self._entries.get(host_id)
            if e is not None:
                e.accepting = False

    # -- surfaces -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The per-host section of the router's ``GET /health``."""
        now = time.monotonic()
        with self._lock:
            entries = {hid: dataclasses.replace(e)
                       for hid, e in self._entries.items()}
        out = {}
        for hid, e in entries.items():
            age = e.age_s(now)
            out[hid] = {
                "base": e.base,
                "schema": e.schema,
                "healthy": self.healthy(hid, now),
                "accepting": e.accepting,
                "inflight_prompts": e.inflight_prompts,
                "queue_pending": e.queue_pending,
                "queue_running": e.queue_running,
                "workers": e.workers,
                "hbm_utilization_max": e.hbm_utilization_max,
                "compile": e.compile_cache,
                "numerics_ok": e.numerics_ok,
                "quarantined_lanes": e.quarantined_lanes,
                "warm_keys": sorted(e.warm_keys),
                "role": e.role,
                "health_age_s": None if age is None else round(age, 3),
                "consecutive_failures": e.consecutive_failures,
                "last_error": e.last_error,
            }
        return out

    def publish_gauges(self) -> None:
        snap = self.snapshot()
        registry.gauge("pa_fleet_hosts", len(snap),
                       help="backends on the router's scoreboard")
        registry.gauge(
            "pa_fleet_hosts_healthy",
            sum(1 for s in snap.values() if s["healthy"]),
            help="backends currently healthy (fresh poll, numerics clean)",
        )
        for hid, s in snap.items():
            registry.gauge("pa_fleet_host_inflight", s["inflight_prompts"],
                           labels={"host": hid},
                           help="in-flight prompts per backend (polled)")
            registry.gauge("pa_fleet_host_accepting",
                           1.0 if s["accepting"] else 0.0,
                           labels={"host": hid},
                           help="drain state per backend (1 = seating)")
            if s["health_age_s"] is not None:
                # The anomaly sentinel's heartbeat-staleness signal
                # (utils/anomaly.py): a host whose last good poll keeps
                # aging is going dark long before fail_after marks it.
                registry.gauge("pa_fleet_host_health_age_s",
                               s["health_age_s"], labels={"host": hid},
                               help="seconds since the backend's last "
                                    "successful health poll")
