"""Role pools: encode / denoise / decode as separately scaled fleet tiers.

The reference has no notion of serving stages: every worker thread runs the
WHOLE sampler loop — text encode, denoise steps, and decode all execute on
whatever device the thread was pinned to (any_device_parallel.py:817-905).
That shape wastes heavy chips on cheap work: a tail VAE decode or a
millisecond text-encode occupies the same accelerator a denoise step needs.

This module is the fleet-level answer (ROADMAP "role disaggregation"): hosts
declare a **role** at registration — ``encode`` (small-chip/CPU hosts
fronting the content-addressed embed cache), ``denoise`` (the lane-batched
heavy chips), ``decode`` (width-bucketed batched decodes) — or the default
``all``, which keeps a host in every pool (a single-pool deployment of
``all`` hosts is bitwise-identical to the pre-role fleet). The router's
placement then becomes per-stage: :class:`RolePools` maintains one
consistent-hash ring per role over the pool's members (same capacity
weighting and warm-affinity semantics as the global ring,
fleet/registry.py), and :func:`suggest_pool_split` sizes the pools from
roofline per-role capacity predictions so "how many decode hosts do I need"
is a computed answer, not a guess.

Stage hand-offs are content-addressed: :class:`StageStore` holds serialized
boundary outputs (cond tensors out of encode, latents out of denoise) under
an md5-of-bytes key — the "latent digest" the journal's stage-lineage
records carry, so a standby router's takeover can re-dispatch a decode from
the journaled denoise output handle without re-denoising, and a missing
handle degrades to local recompute of the upstream stages (bitwise by the
fold_in contract), never an error.

Pure host-side bookkeeping at module level: nothing here imports jax or
numpy until a value is actually serialized.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
from collections import OrderedDict

from ..utils.logging import get_logger

log = get_logger()

# The stage vocabulary. Stage names ARE role names: host.carve_stages ranks
# workflow nodes into exactly these buckets (host.py's SLO class_type
# vocabulary — "TextEncode" / "Sampler" / "Decode"), so a stage's dispatch
# pool is the role of the same name.
ROLES = ("encode", "denoise", "decode")


def normalize_role(raw) -> str:
    """Canonical role string: one of :data:`ROLES` or ``"all"`` (the
    default — member of every pool). Unknown strings raise ``ValueError``
    so a typo'd ``--role dencode`` fails at startup, not at placement."""
    role = str(raw or "all").strip().lower()
    if role in ("", "all"):
        return "all"
    if role not in ROLES:
        raise ValueError(
            f"unknown role {raw!r} (expected one of {('all',) + ROLES})"
        )
    return role


def _gauge(name, value, labels=None, help="") -> None:
    try:
        from ..utils.metrics import registry as _metrics
    except Exception:
        return
    try:
        _metrics.gauge(name, value, labels=labels, help=help)
    except Exception:
        pass


class RolePools:
    """Per-role consistent-hash rings over a :class:`~.registry.FleetRegistry`.

    Role source, in priority order: the role the host registered with
    (``HostInfo.role`` — the ``--role`` knob on server.py riding the
    heartbeat), else the role the host's own ``/health`` advertises (the
    scoreboard's parsed snapshot — covers statically configured
    ``--backends`` hosts that never heartbeat). A host whose role is
    ``all`` joins every pool.

    Rings rebuild lazily: every query recomputes a cheap membership
    signature ``(host_id, role, weight)`` and rebuilds only when it moved —
    the same keys-stay-put churn property as the global ring."""

    def __init__(self, registry, scoreboard=None, vnodes: int = 64):
        self.registry = registry
        self.scoreboard = scoreboard
        self.vnodes = int(vnodes)
        self._lock = threading.Lock()
        self._sig = None                     # guarded-by: _lock
        self._rings: dict[str, "object"] = {}    # guarded-by: _lock
        self._members: dict[str, list[str]] = {}  # guarded-by: _lock

    # -- role resolution -----------------------------------------------------

    def role_of(self, host_id: str) -> str:
        """Effective role of one live host (``"all"`` when undeclared)."""
        return self.membership().get(host_id, "all")

    def _scoreboard_role(self, host_id: str) -> str | None:
        sb = self.scoreboard
        if sb is None:
            return None
        role_of = getattr(sb, "role_of", None)
        if role_of is None:
            return None
        try:
            return role_of(host_id)
        except Exception:
            return None

    def membership(self) -> dict[str, str]:
        """host_id → effective role over the registry's live hosts."""
        out: dict[str, str] = {}
        for hid, info in self.registry.hosts().items():
            role = getattr(info, "role", "all") or "all"
            if role == "all":
                role = self._scoreboard_role(hid) or "all"
            try:
                out[hid] = normalize_role(role)
            except ValueError:
                out[hid] = "all"
        return out

    def disaggregated(self) -> bool:
        """True when any live host declared a specific role — the router's
        gate for stage-carved dispatch. An all-``all`` fleet stays on the
        single-dispatch path bitwise-unchanged."""
        return any(r != "all" for r in self.membership().values())

    # -- rings ---------------------------------------------------------------

    def _refresh(self) -> dict[str, list[str]]:
        from .registry import HashRing

        members_by_role: dict[str, list[str]] = {r: [] for r in ROLES}
        membership = self.membership()
        for hid in sorted(membership):
            role = membership[hid]
            for r in ROLES:
                if role in (r, "all"):
                    members_by_role[r].append(hid)
        try:
            weights = self.registry.capacity_weights()
        except Exception:
            weights = {}
        sig = (
            tuple(sorted(membership.items())),
            tuple(sorted(weights.items())),
        )
        with self._lock:
            if sig != self._sig:
                rings = {}
                for r, hids in members_by_role.items():
                    ring = HashRing(vnodes=self.vnodes)
                    ring.rebuild(hids, weights)
                    rings[r] = ring
                self._rings = rings
                self._members = members_by_role
                self._sig = sig
            return dict(self._members)

    def pool_sizes(self) -> dict[str, int]:
        members = self._refresh()
        return {r: len(members[r]) for r in ROLES}

    def sequence(self, role: str, key: str) -> list[str]:
        """Host preference order for ``key`` within one role's pool —
        primary first, ring order after (the spill/failover order). An
        EMPTY pool falls back to the registry's global ring: a fleet that
        declared denoise+encode hosts but no decode host still decodes
        (on whoever the global ring picks), it just doesn't isolate."""
        self._refresh()
        with self._lock:
            ring = self._rings.get(role)
            seq = ring.sequence(key) if ring is not None else []
        if seq:
            return seq
        return self.registry.sequence(key)

    def publish_gauges(self) -> None:
        """Live pool sizes (``pa_role_pool_size{role=}``) — scrape-time
        publication, same pattern as the server's queue gauges."""
        for role, n in self.pool_sizes().items():
            _gauge("pa_role_pool_size", n, labels={"role": role},
                   help="live hosts in each role pool (all-role hosts count in every pool)")

    def snapshot(self) -> dict:
        """The ``/fleet/hosts`` roles section."""
        members = self._refresh()
        return {
            "disaggregated": self.disaggregated(),
            "pools": {r: list(members[r]) for r in ROLES},
            "membership": self.membership(),
        }


# ---------------------------------------------------------------------------
# pool sizing from roofline per-role capacity
# ---------------------------------------------------------------------------

# Nominal per-role service-time SHAPE when no measured stage histogram
# exists yet: denoise dominates (the full step loop), decode is the VAE tail
# (~1/4 of a step budget at CPU-spec arithmetic intensity), encode is a
# single text-tower pass. Only the RATIOS matter to apportionment; the
# roofline's nominal step time scales all three identically.
_NOMINAL_SHAPE = {"encode": 0.10, "denoise": 1.00, "decode": 0.25}


def suggest_pool_split(total_hosts: int,
                       stage_p50s: dict | None = None,
                       device_kind: str = "",
                       platform: str = "cpu") -> dict[str, int]:
    """Apportion ``total_hosts`` across the role pools proportionally to
    per-role load — measured stage p50s when the SLO histograms have them
    (``encode`` / ``eval`` / ``decode`` stage walls; ``denoise`` accepted as
    an alias for ``eval``), else the roofline-nominal shape scaled by
    :func:`utils.roofline.nominal_step_time_s` for the platform.

    Largest-remainder apportionment; every pool gets at least one host when
    ``total_hosts >= 3`` (a pool sized zero would silently fall back to the
    global ring and un-disaggregate that stage)."""
    total = max(0, int(total_hosts))
    if total == 0:
        return {r: 0 for r in ROLES}

    p = dict(stage_p50s or {})
    loads = {
        "encode": p.get("encode"),
        "denoise": p.get("denoise", p.get("eval")),
        "decode": p.get("decode"),
    }
    if not all(isinstance(v, (int, float)) and v > 0 for v in loads.values()):
        try:
            from ..utils import roofline

            t = roofline.nominal_step_time_s(device_kind, platform)
        except Exception:
            t = 1.0
        for r, v in loads.items():
            if not (isinstance(v, (int, float)) and v > 0):
                loads[r] = _NOMINAL_SHAPE[r] * t

    weight = sum(loads.values())
    quotas = {r: total * loads[r] / weight for r in ROLES}
    split = {r: int(quotas[r]) for r in ROLES}
    if total >= len(ROLES):
        for r in ROLES:
            split[r] = max(1, split[r])
    # Largest remainder fills (or trims, after the min-1 floor) to total.
    def _by_remainder(reverse: bool):
        return sorted(ROLES, key=lambda r: quotas[r] - int(quotas[r]),
                      reverse=reverse)

    while sum(split.values()) < total:
        for r in _by_remainder(reverse=True):
            if sum(split.values()) >= total:
                break
            split[r] += 1
    while sum(split.values()) > total:
        for r in _by_remainder(reverse=False):
            if sum(split.values()) <= total:
                break
            floor = 1 if total >= len(ROLES) else 0
            if split[r] > floor:
                split[r] -= 1
    return split


# ---------------------------------------------------------------------------
# content-addressed stage hand-off store
# ---------------------------------------------------------------------------

DEFAULT_STORE_BYTES = 256 * 1024 * 1024


def store_budget_bytes() -> int:
    """``PA_STAGE_STORE_BYTES`` (bytes; 0 disables the store — every stage
    hand-off then degrades to recompute-locally, still correct)."""
    raw = os.environ.get("PA_STAGE_STORE_BYTES")
    if raw is None:
        return DEFAULT_STORE_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_STORE_BYTES


def _to_host_arrays(value):
    """Device arrays → numpy, recursively, so a stage boundary value
    serializes without shipping a live device buffer (and deserializes on a
    host with a different mesh). Containers keep their shape; jnp consumers
    accept numpy inputs transparently."""
    if isinstance(value, tuple):
        return tuple(_to_host_arrays(v) for v in value)
    if isinstance(value, list):
        return [_to_host_arrays(v) for v in value]
    if isinstance(value, dict):
        return {k: _to_host_arrays(v) for k, v in value.items()}
    if hasattr(value, "__array__") and not isinstance(value, (str, bytes)):
        import numpy as np

        return np.asarray(value)
    return value


def serialize_value(value) -> bytes:
    """One node-output tuple → wire bytes (pickle over numpy-converted
    leaves). Raises on unpicklable values — callers treat that as "this
    boundary can't hand off" and skip the handle, not as an error."""
    return pickle.dumps(_to_host_arrays(value), protocol=4)


def deserialize_value(blob: bytes):
    return pickle.loads(blob)


def content_key(blob: bytes) -> str:
    """The content address: md5 hex of the serialized bytes — the "latent
    digest" a journal stage record carries for a denoise output, and the
    cond digest for an encode output."""
    return hashlib.md5(blob).hexdigest()


class StageStore:
    """Byte-bounded LRU of serialized stage boundary values, keyed by
    content address. Every backend owns one (module-level :data:`store`):
    a host PUTs the boundary outputs of the stage it just ran and serves
    them to the next stage's host over ``GET /stage/{key}``; a missing key
    is a miss, never an error (the fetching host recomputes locally)."""

    def __init__(self, max_bytes: int | None = None):
        self.max_bytes = store_budget_bytes() if max_bytes is None else int(max_bytes)
        self._lock = threading.Lock()
        self._blobs: "OrderedDict[str, bytes]" = OrderedDict()  # guarded-by: _lock
        self._bytes = 0       # guarded-by: _lock
        self.hits = 0         # guarded-by: _lock
        self.misses = 0       # guarded-by: _lock
        self.evictions = 0    # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def put(self, blob: bytes) -> str:
        """Insert one serialized value; returns its content key. Oversized
        blobs (> the whole budget) are hashed but not retained."""
        key = content_key(blob)
        if not self.enabled or len(blob) > self.max_bytes:
            return key
        with self._lock:
            if key in self._blobs:
                self._blobs.move_to_end(key)
                return key
            self._blobs[key] = blob
            self._bytes += len(blob)
            while self._bytes > self.max_bytes and self._blobs:
                _, old = self._blobs.popitem(last=False)
                self._bytes -= len(old)
                self.evictions += 1
        return key

    def put_value(self, value) -> str | None:
        """Serialize + insert; ``None`` when the value can't serialize (a
        model handle at a stage boundary) — the caller simply doesn't
        advertise a handle for that output."""
        try:
            blob = serialize_value(value)
        except Exception:
            return None
        return self.put(blob)

    def get(self, key: str) -> bytes | None:
        with self._lock:
            blob = self._blobs.get(key)
            if blob is None:
                self.misses += 1
                return None
            self._blobs.move_to_end(key)
            self.hits += 1
            return blob

    def get_value(self, key: str):
        blob = self.get(key)
        return None if blob is None else deserialize_value(blob)

    def clear(self) -> None:
        with self._lock:
            self._blobs.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled, "entries": len(self._blobs),
                "bytes": self._bytes, "budget_bytes": self.max_bytes,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions,
            }

    def publish_gauges(self) -> None:
        s = self.stats()
        _gauge("pa_role_stage_store_bytes", s["bytes"],
               help="resident bytes in the content-addressed stage hand-off store")
        _gauge("pa_role_stage_store_entries", s["entries"],
               help="entries in the content-addressed stage hand-off store")


# The process-wide store every server/backends shares (one per process, the
# same pattern as models/embed_cache.cache).
store = StageStore()
