"""Fleet tier: multi-host front-door routing over N workflow servers.

- fleet/registry.py   — membership: consistent-hash ring + heartbeats
- fleet/scoreboard.py — per-host health polled from ``GET /health``
- fleet/router.py     — the front-door process: warm-affinity placement,
                        health-driven admission, lossless failover

The router owns no model state; backends are plain ``server.py`` processes
(``--fleet-router`` makes them register elastically). See README "Fleet
serving".
"""

from .journal import JournalFollower, PromptJournal
from .registry import (
    FleetRegistry,
    HashRing,
    HeartbeatClient,
    ledger_capacity_weights,
)
from .router import FleetRouter, make_router, model_key
from .scoreboard import Scoreboard

__all__ = [
    "FleetRegistry",
    "FleetRouter",
    "HashRing",
    "HeartbeatClient",
    "JournalFollower",
    "PromptJournal",
    "Scoreboard",
    "ledger_capacity_weights",
    "make_router",
    "model_key",
]
