"""Fleet tier: multi-host front-door routing over N workflow servers.

- fleet/registry.py   — membership: consistent-hash ring + heartbeats
- fleet/scoreboard.py — per-host health polled from ``GET /health`` (+ the
                        /metrics scrape cache behind ``GET /fleet/metrics``)
- fleet/router.py     — the front-door process: warm-affinity placement,
                        health-driven admission, lossless failover
- fleet/roles.py      — disaggregated role pools (encode/denoise/decode):
                        per-pool rings, the roofline pool-split suggestion,
                        and the content-addressed stage store
- fleet/journal.py    — the durable prompt journal + lease (router HA)
- fleet/twin.py       — seeded arrival processes + the discrete-event
                        traffic twin (stdlib-only, standalone-loadable)

The router owns no model state; backends are plain ``server.py`` processes
(``--fleet-router`` makes them register elastically). See README "Fleet
serving".
"""

from .journal import JournalFollower, PromptJournal
from .registry import (
    FleetRegistry,
    HashRing,
    HeartbeatClient,
    ledger_capacity_weights,
)
from .roles import ROLES, RolePools, StageStore, normalize_role, suggest_pool_split
from .router import FleetRouter, make_router, model_key
from .scoreboard import Scoreboard

__all__ = [
    "FleetRegistry",
    "FleetRouter",
    "HashRing",
    "HeartbeatClient",
    "JournalFollower",
    "PromptJournal",
    "ROLES",
    "RolePools",
    "Scoreboard",
    "StageStore",
    "ledger_capacity_weights",
    "make_router",
    "model_key",
    "normalize_role",
    "suggest_pool_split",
]
