"""Durable prompt journal + lease: the fleet router's crash story.

PR 7 made the router the fleet's front door — and its single point of
failure (ROADMAP "Fleet tier hardening" item 1): every retained submission,
placement decision, and collected history entry lived in one process's
memory. This module makes that state DURABLE and FOLLOWABLE:

- **``pa-fleet-journal/v1``**: an append-only JSONL file the active router
  writes at the three lifecycle edges — ``submit`` (the full graph +
  extra_data + placement key: everything needed to re-place the prompt from
  nothing), ``dispatch`` (which host/backend_pid owns it now), ``resolve``
  (the final history entry, verbatim — so a router that never saw the
  prompt live can still serve ``GET /history/{id}``). Appends are
  line-atomic (single ``write`` of one ``\\n``-terminated line) and flushed
  per record; ``PA_JOURNAL_FSYNC=1`` adds an fsync per append for real
  crash-consistency on shared storage.
- **a lease**: ``<journal>.lease`` rewritten atomically by the active
  router every monitor sweep (wall-clock epoch stamps — the one clock two
  processes share; monotonic clocks are process-local). A standby that sees
  the lease go stale past its TTL declares the primary dead and takes over.
- **replay**: folding a journal left-to-right reconstructs every prompt's
  last known state. Unresolved prompts re-enter the standby's normal
  placement machinery — completed work is re-collected from live backends
  (the backend still holds the history entry under the recorded
  backend_pid), genuinely lost work replays from step 0 on a sibling, and
  the round-10 fold_in RNG contract makes the replayed latents bitwise
  equal to the uninterrupted run. Router-kill-mid-denoise loses zero
  prompts, which dryrun §18 and the chaos smoke gate on.

Tailing works over a SHARED PATH (both routers see one file) or over HTTP:
the active router serves ``GET /journal?offset=N`` (raw bytes from offset)
and :meth:`JournalFollower.poll` appends whatever is new to the standby's
local copy — same fold, different transport.

Pure stdlib; nothing here imports jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

from ..utils import faults
from ..utils.logging import get_logger

log = get_logger()

JOURNAL_SCHEMA = "pa-fleet-journal/v1"

# Lifecycle edges. "takeover" marks a standby assuming the lease (an audit
# row — replay treats it as a no-op for prompt state). The stage_* pair is
# the STAGE LINEAGE of a role-pool dispatch (fleet/roles.py): stage_resolve
# banks a completed stage's content-addressed output handles (embed-cache /
# latent digests), stage_dispatch records which pool host owns the NEXT
# stage — so a standby's takeover resumes a prompt from its last completed
# stage (a dead decode host re-dispatches from the journaled denoise
# handles; nothing re-denoises, and what does replay is bitwise by fold_in).
EVENTS = ("submit", "dispatch", "resolve", "takeover",
          "stage_dispatch", "stage_resolve")


class PromptJournal:
    """Append side + replay side of one journal file."""

    def __init__(self, path: str, fsync: bool | None = None):
        self.path = path
        self.lease_path = path + ".lease"
        if fsync is None:
            fsync = os.environ.get("PA_JOURNAL_FSYNC") == "1"
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        self._f = None

    # -- append side ---------------------------------------------------------

    def _file(self):
        if self._f is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "ab")
        return self._f

    def append(self, ev: str, pid: str, **fields) -> None:
        """One journal record. Best-effort by contract beyond the flush: a
        full disk degrades durability, never availability (the in-memory
        router keeps serving; the log says so)."""
        assert ev in EVENTS, f"unknown journal event {ev!r}"
        rec = {"schema": JOURNAL_SCHEMA, "ev": ev, "pid": pid,
               # palint: allow[observability] wall-clock is the ONE clock a
               # failover pair shares (monotonic is process-local)
               "ts": time.time(), **fields}
        line = (json.dumps(rec, default=str) + "\n").encode()
        # Fault site (utils/faults.py): a router crash mid-write leaves a
        # TORN tail — mode=truncate writes half the line with no newline
        # (the record is lost; the NEXT append concatenates onto it, so one
        # more line is unparseable — exactly the disk state a real crash +
        # restart produces); mode=garble keeps the length and newline but
        # NULs the middle (unparseable, neighbors intact). Either way the
        # fold/replay side must skip the damage and the standby's takeover
        # must still lose zero prompts — the chaos-matrix assertion.
        action = faults.check("journal-corrupt", key=ev)
        if action is not None:
            if action.mode == "garble":
                mid = max(1, len(line) // 3)
                line = line[:mid] + b"\x00" * mid + line[2 * mid:]
            else:  # truncate (default): torn tail, no newline
                line = line[: max(1, len(line) // 2)]
        # Slow-disk fault site: the sleep sits INSIDE the timed region so
        # the injected fsync stall lands in pa_disk_append_seconds — the
        # exact latency the anomaly sentinel's disk_append_p95 watch reads.
        slow = faults.check("slow-disk", key=ev)
        t0 = time.perf_counter()
        try:
            with self._lock:
                if slow is not None:
                    slow.sleep()
                f = self._file()
                f.write(line)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
        except OSError as e:
            log.error("journal append failed (%s): %s", self.path, e)
        try:
            from ..utils.metrics import registry
            registry.histogram("pa_disk_append_seconds",
                               time.perf_counter() - t0,
                               labels={"target": "journal"},
                               help="journal/ledger append wall time")
        except Exception:  # pragma: no cover - metrics are best-effort
            pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    # -- lease ---------------------------------------------------------------

    def write_lease(self, router_id: str) -> None:
        """Atomic replace — a reader never sees a half-written lease."""
        tmp = f"{self.lease_path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(os.path.abspath(self.lease_path)),
                        exist_ok=True)
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    # palint: allow[observability] lease stamps compare across
                    # router processes — wall-clock by necessity
                    "router_id": router_id, "ts": time.time(),
                    "pid": os.getpid(),
                }))
            os.replace(tmp, self.lease_path)
        except OSError as e:
            log.error("lease write failed (%s): %s", self.lease_path, e)

    def read_lease(self) -> dict | None:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def lease_stale(self, ttl_s: float, holder_not: str | None = None) -> bool:
        """True when no live lease exists: missing/corrupt, older than
        ``ttl_s``, or (with ``holder_not``) held by that id — a router never
        treats its OWN lease as a dead primary."""
        lease = self.read_lease()
        if lease is None:
            return True
        if holder_not is not None and lease.get("router_id") == holder_not:
            return False
        try:
            # palint: allow[observability] lease age vs another process's
            # wall-clock stamp — the cross-process clock
            age = time.time() - float(lease.get("ts", 0))
        except (TypeError, ValueError):
            return True
        return age > ttl_s

    # -- replay side ---------------------------------------------------------

    @staticmethod
    def iter_records(path: str):
        """Parsed records in append order; a torn final line (crash mid-
        write) is skipped, never fatal."""
        try:
            with open(path, "rb") as f:
                for raw in f:
                    try:
                        rec = json.loads(raw)
                    except ValueError:
                        continue  # torn tail / garbage line
                    if isinstance(rec, dict) and rec.get("pid"):
                        yield rec
        except OSError:
            return

    @classmethod
    def fold(cls, records) -> dict[str, dict]:
        """pid → last known state, folding lifecycle edges left-to-right:
        ``{"phase": submit|dispatch|resolve, "graph", "extra", "key",
        "number", "host", "backend_pid", "entry", "status", "stages",
        "stage", "stage_idx"}``. ``stages`` is the accumulated stage
        lineage (one row per completed stage, content-addressed handles
        included); ``stage``/``stage_idx`` name the stage the CURRENT
        dispatch owns — None for unstaged prompts."""
        table: dict[str, dict] = {}
        for rec in records:
            ev = rec.get("ev")
            pid = rec["pid"]
            st = table.get(pid)
            if ev == "submit":
                table[pid] = {
                    "phase": "submit", "graph": rec.get("graph"),
                    "extra": rec.get("extra"), "key": rec.get("key"),
                    "number": rec.get("number"), "host": None,
                    "backend_pid": None, "entry": None, "status": None,
                    "stages": [], "stage": None, "stage_idx": None,
                }
            elif ev == "dispatch" and st is not None:
                st["phase"] = "dispatch"
                st["host"] = rec.get("host")
                st["backend_pid"] = rec.get("backend_pid")
                st["stage"] = rec.get("stage")
                st["stage_idx"] = rec.get("stage_idx")
            elif ev == "stage_dispatch" and st is not None:
                # Ownership moves to the next stage's pool host; replay
                # re-collects from HERE, with the lineage below feeding the
                # handles a restarted stage needs.
                st["phase"] = "dispatch"
                st["host"] = rec.get("host")
                st["backend_pid"] = rec.get("backend_pid")
                st["stage"] = rec.get("stage")
                st["stage_idx"] = rec.get("stage_idx")
            elif ev == "stage_resolve" and st is not None:
                st.setdefault("stages", []).append({
                    "stage": rec.get("stage"),
                    "stage_idx": rec.get("stage_idx"),
                    "host": rec.get("host"),
                    "handles": rec.get("handles"),
                })
            elif ev == "resolve" and st is not None:
                st["phase"] = "resolve"
                st["entry"] = rec.get("entry")
                st["status"] = rec.get("status")
        return table

    def replay(self) -> dict[str, dict]:
        return self.fold(self.iter_records(self.path))


class JournalFollower:
    """HTTP tail of an active router's journal (``GET /journal?offset=N``)
    into a local file a standby's :class:`PromptJournal` then replays — the
    no-shared-filesystem deployment. ``poll()`` returns how many bytes
    landed; transport errors return 0 (the primary being down is exactly
    when the standby must keep deciding on what it already has)."""

    def __init__(self, primary_base: str, local_path: str,
                 timeout_s: float = 5.0):
        self.primary_base = primary_base.rstrip("/")
        self.local_path = local_path
        self.timeout_s = float(timeout_s)
        self.offset = 0
        self.unreachable = False   # the standby's primary-death signal
        if os.path.exists(local_path):
            self.offset = os.path.getsize(local_path)

    def poll(self) -> int:
        try:
            with urllib.request.urlopen(
                f"{self.primary_base}/journal?offset={self.offset}",
                timeout=self.timeout_s,
            ) as r:
                chunk = r.read()
        except (OSError, ValueError):
            self.unreachable = True
            return 0
        self.unreachable = False
        if not chunk:
            return 0
        d = os.path.dirname(os.path.abspath(self.local_path))
        os.makedirs(d, exist_ok=True)
        with open(self.local_path, "ab") as f:
            f.write(chunk)
        self.offset += len(chunk)
        return len(chunk)
