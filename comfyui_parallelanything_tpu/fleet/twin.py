"""Traffic twin: replay an arrival trace against predicted per-host capacity.

The open-loop loadgen (scripts/loadgen.py) measures latency-under-load by
actually driving a fleet; this module predicts the same curves in SECONDS,
with NO devices — a discrete-event simulation of the router's
placement/queueing over per-host service times, so placement/admission/
scaling policies can be evaluated offline (the capacity-prediction story of
PAPERS.md arxiv 2412.14374; ROADMAP round-15 "open-loop traffic twin").
Twin-predicted p95 vs measured p95 is a checkable, bankable number
(``scripts/twin_report.py --check/--bank`` — ci_tier1-gated within the
declared error band).

Three pieces:

- **arrival processes** (:func:`gen_arrivals`): seeded, deterministic —
  Poisson (exponential inter-arrivals at ``rps``), bursty ON-OFF (Poisson
  at ``rps`` during ON windows, silent during OFF — the diurnal-burst
  rehearsal), and trace replay (:func:`arrivals_from_journal` lifts submit
  timestamps out of a recorded fleet journal). scripts/loadgen.py loads this
  file standalone and fires REAL requests on the same schedule the twin
  replays — one generator, two consumers, so "the same seeded arrival
  trace" is true by construction.
- **the simulation** (:func:`simulate`): per-host pools of ``workers``
  servers with deterministic service times; each arrival is placed on the
  host that can START it earliest (ring affinity collapses to this under
  one model key: the primary while free, spill-to-least-loaded when
  saturated — the router's admission shape without its HTTP). Latency =
  queue wait + service; the output is the same p50/p95/p99 curve shape the
  open-loop loadgen emits.
- **per-host capacity** (:func:`host_service_times`): tiered like every
  calibration consumer — (1) the roofline prediction
  (``utils/roofline.predict_time_s`` × the calibration store) when the
  record carries per-host FLOPs/bytes rows; (2) the record's own measured
  per-host service p50 (the ledger-calibrated fallback — what the CPU smoke
  exercises, where no compiled-program roofline rows exist for the toy
  graphs); (3) the record-wide mean. Sources are named in the output so a
  twin report says WHAT predicted, not just how well.

Import discipline: module level is stdlib-only and free of package-relative
imports (the utils/roofline.py contract) — scripts/loadgen.py and
scripts/twin_report.py load this file standalone by path; utils/roofline.py
is itself loaded lazily by path for the prediction tier.
"""

from __future__ import annotations

import heapq
import json
import os
import random

ARRIVALS_SCHEMA = "pa-arrivals/v1"

ARRIVAL_KINDS = ("poisson", "onoff", "replay")


def _percentile(samples, q: float) -> float:
    """Nearest-rank percentile (the scripts/loadgen.py convention — the twin
    and the measurement must rank identically or the error band lies)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


# ---------------------------------------------------------------------------
# arrival processes (seeded, deterministic)
# ---------------------------------------------------------------------------


def gen_arrivals(kind: str, *, rps: float, duration_s: float, seed: int = 0,
                 on_s: float = 1.0, off_s: float = 1.0) -> list[float]:
    """Arrival offsets (seconds from the rung's start), sorted ascending.

    ``poisson``: exponential inter-arrival gaps at ``rps`` — the open-loop
    memoryless baseline. ``onoff``: the same process gated by an ON/OFF
    square wave (``on_s`` busy, ``off_s`` silent) with the ON rate scaled so
    the OFFERED average stays ``rps`` — burstiness changes the queue, not
    the load, which is exactly the comparison the twin exists to predict.
    Deterministic in (kind, rps, duration, seed, on_s, off_s): two calls
    yield the identical schedule."""
    if kind not in ("poisson", "onoff"):
        raise ValueError(f"unknown arrival kind {kind!r} "
                         f"(have: poisson, onoff; replay loads a file)")
    rps = float(rps)
    duration_s = float(duration_s)
    if rps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(int(seed))
    out: list[float] = []
    if kind == "poisson":
        t = rng.expovariate(rps)
        while t < duration_s:
            out.append(round(t, 6))
            t += rng.expovariate(rps)
        return out
    # onoff: ON windows carry the whole offered load.
    on_s = max(1e-3, float(on_s))
    off_s = max(0.0, float(off_s))
    duty = on_s / (on_s + off_s)
    rate_on = rps / max(1e-9, duty)
    t = 0.0
    while t < duration_s:
        # one ON window
        w = rng.expovariate(rate_on)
        while w < on_s and t + w < duration_s:
            out.append(round(t + w, 6))
            w += rng.expovariate(rate_on)
        t += on_s + off_s
    out.sort()
    return out


def arrivals_from_journal(path: str) -> list[float]:
    """Trace replay: submit-record timestamps from a recorded fleet journal
    (``pa-fleet-journal/v1`` JSONL), as offsets from the first submit —
    yesterday's real traffic becomes today's load schedule. Torn/garbage
    lines are skipped (the journal's own replay discipline)."""
    stamps: list[float] = []
    try:
        with open(path, "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if (isinstance(rec, dict) and rec.get("ev") == "submit"
                        and isinstance(rec.get("ts"), (int, float))):
                    stamps.append(float(rec["ts"]))
    except OSError:
        return []
    if not stamps:
        return []
    t0 = min(stamps)
    return sorted(round(t - t0, 6) for t in stamps)


def save_arrivals(path: str, rungs: list[dict], *, kind: str,
                  seed: int | None = None) -> str:
    """Persist an arrival schedule (``--arrivals-out``): one JSON document
    ``{"schema", "kind", "seed", "rungs": [{"rps", "duration_s",
    "offsets"}]}`` — the twin (and a later replay run) reads it back."""
    doc = {"schema": ARRIVALS_SCHEMA, "kind": kind, "seed": seed,
           "rungs": rungs}
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def load_arrivals(path: str) -> dict:
    """An ``--arrivals-in`` file: either a saved arrivals document (schema
    pa-arrivals/v1) or a raw fleet journal (detected by its records) —
    normalized to the arrivals-document shape with one rung."""
    try:
        with open(path) as f:
            head = f.read(4096)
    except OSError as e:
        raise ValueError(f"cannot read arrivals file {path!r}: {e}") from e
    if '"pa-arrivals/v1"' in head:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc.get("rungs"), list):
            raise ValueError(f"{path!r}: arrivals document has no rungs")
        return doc
    offsets = arrivals_from_journal(path)
    if not offsets:
        raise ValueError(
            f"{path!r} is neither a pa-arrivals/v1 document nor a journal "
            f"with submit records"
        )
    dur = max(offsets) or 1.0
    return {"schema": ARRIVALS_SCHEMA, "kind": "replay", "seed": None,
            "rungs": [{"rps": round(len(offsets) / dur, 4),
                       "duration_s": round(dur, 3), "offsets": offsets}]}


# ---------------------------------------------------------------------------
# the discrete-event simulation
# ---------------------------------------------------------------------------


ROLE_STAGES = ("encode", "denoise", "decode")


def simulate(arrivals: list[float], hosts: list[dict],
             percentiles=(50, 95, 99), overhead_s: float = 0.0) -> dict:
    """Replay ``arrivals`` over per-host worker pools.

    ``hosts``: ``[{"host_id", "service_s", "workers"}]`` — ``service_s`` is
    the deterministic per-request service time, ``workers`` the host's
    concurrent servers (the backend's prompt-worker pool). Placement is the
    router's admission shape under one model key: every arrival goes to the
    host that can START it earliest (primary affinity while free ≡ earliest
    start; saturation spill ≡ least-loaded) — FIFO per worker, no preemption.

    Host rows may also carry ``"role"`` (fleet/roles.py): when any host
    declares a role other than ``all``, the simulation becomes the
    DISAGGREGATED tandem — each request flows encode → denoise → decode,
    each stage placed earliest-start within that stage's pool (role match
    plus ``all`` generalists, who share one worker heap across every stage
    they serve), and a stage's completion time is the next stage's arrival
    — the hand-off edge. A host's ``service_s`` is its per-STAGE service
    time there (an encode host's measured p50 is encode work by
    construction). An all-``all`` fleet takes the single-queue path
    unchanged, bit-for-bit.

    ``overhead_s`` is a constant per-request client-side term (HTTP +
    history-poll cadence — what loadgen's ``collect`` residual measures),
    added to every latency but occupying no server: the twin predicts the
    CLIENT's end-to-end curve, which is what the measured record carries.

    Returns the measured-curve shape: latency percentiles, achieved rps,
    mean queue wait, and per-host request counts — directly comparable to
    one open-loop loadgen rung."""
    pools: dict[str, list[float]] = {}
    service: dict[str, float] = {}
    role_of: dict[str, str] = {}
    for h in hosts:
        hid = str(h.get("host_id"))
        workers = max(1, int(h.get("workers") or 1))
        pools[hid] = [0.0] * workers  # heap of worker-free times
        service[hid] = max(1e-6, float(h.get("service_s") or 0.0))
        role_of[hid] = str(h.get("role") or "all")
    if not pools:
        raise ValueError("simulate() needs at least one host")
    for heap in pools.values():
        heapq.heapify(heap)
    disaggregated = any(r != "all" for r in role_of.values())
    # Stage hand-off edges: per-stage candidate pools, empty stages elided
    # (a fleet with no encode specialists and no generalists has no encode
    # hop to model).
    stage_pools = [
        [hid for hid in pools if role_of[hid] in (stage, "all")]
        for stage in ROLE_STAGES
    ] if disaggregated else [list(pools)]
    stage_pools = [p for p in stage_pools if p]
    lat: list[float] = []
    waits: list[float] = []
    served: dict[str, int] = {hid: 0 for hid in pools}
    end = 0.0
    for t in arrivals:
        t_stage = t
        wait = 0.0
        for pool in stage_pools:
            # Earliest possible START across the stage's hosts; service
            # time breaks ties (a faster host that starts at the same
            # instant finishes first).
            best_hid = min(
                pool,
                key=lambda hid: (max(pools[hid][0], t_stage), service[hid]),
            )
            heap = pools[best_hid]
            free = heapq.heappop(heap)
            start = max(free, t_stage)
            done = start + service[best_hid]
            heapq.heappush(heap, done)
            wait += start - t_stage
            served[best_hid] += 1
            t_stage = done  # the hand-off: next stage arrives at completion
        lat.append(t_stage - t + max(0.0, float(overhead_s)))
        waits.append(wait)
        end = max(end, t_stage)
    out = {
        "requests": len(arrivals),
        "wall_s": round(end, 6),
        "achieved_rps": round(len(arrivals) / end, 4) if end > 0 else None,
        "queue_wait_mean_s": (
            round(sum(waits) / len(waits), 6) if waits else 0.0
        ),
        "hosts": served,
    }
    for q in percentiles:
        out[f"latency_p{q}_s"] = round(_percentile(lat, q), 6)
    return out


# ---------------------------------------------------------------------------
# per-host capacity (the roofline/calibration tier)
# ---------------------------------------------------------------------------


def _load_roofline():
    """utils/roofline.py loaded standalone by file path (its module level is
    stdlib-only and free of package-relative imports by contract) — the twin
    must predict without jax, over a wedged tunnel, from just the ledger."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "utils", "roofline.py",
    )
    spec = importlib.util.spec_from_file_location("pa_roofline_twin", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def host_service_times(record: dict, calib: dict | None = None) -> list[dict]:
    """Per-host ``[{"host_id", "service_s", "workers", "source"}]`` from an
    openloop ledger record's ``hosts`` section. Tiered:

    1. ``roofline``: the host row carries ``flops``/``bytes_accessed`` (+
       optional ``device_kind``/``platform``/``n_devices``) — priced through
       ``roofline.predict_time_s`` × the calibration store's scale for
       (``program`` = the row's ``roofline_program`` or ``rung:openloop``,
       platform, shape bucket);
    2. ``measured``: the row's ``service_p50_s`` (per-request exec p50 the
       loadgen clients collected off the history entries — the fleet's own
       same-workload measurement, the ledger-calibration analog);
    3. ``mean``: the record-wide ``service_p50_s``.

    Hosts with none of the three are dropped (a host that served nothing
    has no capacity evidence)."""
    rows = record.get("hosts") or {}
    fallback = record.get("service_p50_s")
    roofline = None
    out: list[dict] = []
    for hid, row in rows.items():
        if not isinstance(row, dict):
            continue
        workers = int(row.get("workers") or 1)
        flops = row.get("flops")
        if isinstance(flops, (int, float)) and flops > 0:
            if roofline is None:
                roofline = _load_roofline()
            spec = roofline.platform_spec(
                str(row.get("device_kind") or ""),
                str(row.get("platform") or "cpu"),
            )
            pred = roofline.predict_time_s(
                flops, row.get("bytes_accessed"), spec,
                n_devices=int(row.get("n_devices") or 1),
            )
            program = str(row.get("roofline_program") or "rung:openloop")
            scale = roofline.calibration_scale(
                calib if calib is not None else roofline.load_calibration(),
                program, spec.get("platform") or "cpu",
                roofline.shape_bucket(flops),
            )
            out.append({"host_id": hid,
                        "service_s": pred["predicted_s"] * scale,
                        "workers": workers, "source": "roofline",
                        "role": str(row.get("role") or "all")})
            continue
        svc = row.get("service_p50_s")
        if isinstance(svc, (int, float)) and svc > 0:
            out.append({"host_id": hid, "service_s": float(svc),
                        "workers": workers, "source": "measured",
                        "role": str(row.get("role") or "all")})
            continue
        if isinstance(fallback, (int, float)) and fallback > 0:
            out.append({"host_id": hid, "service_s": float(fallback),
                        "workers": workers, "source": "mean",
                        "role": str(row.get("role") or "all")})
    return out


# ---------------------------------------------------------------------------
# record replay (the twin_report.py engine)
# ---------------------------------------------------------------------------


def rung_arrivals(rung: dict, *, kind: str, seed: int | None) -> list[float]:
    """One curve rung's arrival schedule: verbatim offsets when the record
    carries them, else regenerated from the stored (kind, seed, rps,
    duration) — bit-identical to the loadgen run's by the seeded-generator
    contract."""
    offsets = rung.get("offsets")
    if isinstance(offsets, list) and offsets:
        return [float(t) for t in offsets]
    if kind == "replay":
        # A replay rung IS its offsets — nothing to regenerate. Empty means
        # unreplayable (the caller skips the rung), never a generator call
        # (gen_arrivals rejects the kind, and the CI gate must SKIP, not
        # crash, on a degenerate banked record).
        return []
    # The REQUESTED rate seeds the generator (rps_offered is the realized
    # arrivals/duration — close, but regeneration must use the same input).
    return gen_arrivals(
        kind, rps=float(rung.get("rps") or rung.get("rps_offered") or 0.0),
        duration_s=float(rung.get("duration_s") or 0.0),
        seed=int(seed or 0),
        on_s=float(rung.get("on_s") or 1.0),
        off_s=float(rung.get("off_s") or 1.0),
    )


def replay_record(record: dict, calib: dict | None = None) -> dict | None:
    """Replay one ``kind="openloop"`` ledger record through the twin:
    regenerate each rung's arrivals, price the hosts, simulate, and compare
    predicted vs measured p95 per rung. None when the record carries no
    usable hosts or rungs (nothing to predict against)."""
    ol = record.get("openloop") or {}
    rungs = ol.get("curve") or []
    hosts = host_service_times(record, calib)
    if not hosts or not rungs:
        return None
    kind = str(ol.get("kind") or "poisson")
    seed = ol.get("seed")
    # The record's calibrated client-side constant (loadgen computes it at
    # the lowest offered rate, where queueing is ~0 and the residual is
    # pure transport + poll cadence).
    overhead = float(ol.get("client_overhead_s") or 0.0)
    out_rungs: list[dict] = []
    for rung in rungs:
        arrivals = rung_arrivals(rung, kind=kind, seed=seed)
        if not arrivals:
            continue
        sim = simulate(arrivals, hosts, overhead_s=overhead)
        measured = rung.get("latency_p95_s")
        err = None
        if isinstance(measured, (int, float)) and measured > 0:
            err = abs(sim["latency_p95_s"] - measured) / measured
        out_rungs.append({
            "rps_offered": rung.get("rps_offered") or rung.get("rps"),
            "arrivals": len(arrivals),
            "twin_p50_s": sim["latency_p50_s"],
            "twin_p95_s": sim["latency_p95_s"],
            "twin_p99_s": sim.get("latency_p99_s"),
            "measured_p50_s": rung.get("latency_p50_s"),
            "measured_p95_s": measured,
            "measured_p99_s": rung.get("latency_p99_s"),
            "p95_err": None if err is None else round(err, 4),
        })
    if not out_rungs:
        return None
    errs = [r["p95_err"] for r in out_rungs if r["p95_err"] is not None]
    return {
        "kind": kind,
        "seed": seed,
        "client_overhead_s": overhead,
        "hosts": hosts,
        "rungs": out_rungs,
        "p95_err_max": round(max(errs), 4) if errs else None,
        "band": record.get("twin_band"),
    }
