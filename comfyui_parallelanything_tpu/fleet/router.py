"""Fleet front door: route prompts across N backend workflow servers.

The reference (and this repo's server.py) is one process: one prompt queue,
one set of loaded models, throughput capped at one host and every in-flight
prompt lost with it on a crash. This router is the fleet tier above that —
a thin, stdlib-only HTTP process that owns NO model state, only placement,
admission, and failover bookkeeping:

- **placement is warm-affinity**: consistent hash on the MODEL identity of
  the prompt graph (fleet/registry.py ring), so every prompt for one model
  lands on the same primary host and that host's compiled step programs and
  pinned weights stay resident (the keep-programs-warm economics of
  PAPERS.md arxiv 2412.14374 — re-staging a model on a cold host costs
  seconds-to-minutes of compile + weight placement). When the primary is
  saturated the prompt SPILLS to the next host clockwise on the ring —
  bounded queueing beats unbounded affinity.
- **admission is health-driven**: every decision reads the per-host
  scoreboard (fleet/scoreboard.py) polled from the backends' existing
  ``GET /health`` documents — queue depth, drain state, HBM watermark,
  numerics verdict — with staleness-aware backoff; no healthy host means an
  explicit 503, never a silently growing queue.
- **failover is lossless**: the router keeps each prompt's submission
  (graph + extra_data) until its history entry is fetched; when a host dies
  mid-denoise (heartbeat expiry, health-poll failures, refused proxies) its
  in-flight prompts are re-submitted to the next ring host. The replay is
  from step 0 on the sibling, and the round-10 RNG contract (every
  stochastic step key is ``fold_in(request rng, step)`` — output is a pure
  function of (request, step), never of occupancy or history) makes the
  re-run's final latent bitwise-equal to an uninterrupted run, which
  ``__graft_entry__`` §16 asserts by killing a backend mid-run.

Client protocol is the same ComfyUI subset server.py speaks — ``POST
/prompt`` returns a router-scoped ``prompt_id`` that stays stable across
failovers, and ``GET /history/{id}`` serves the completed entry (annotated
with ``status.fleet``: serving host, attempts, failovers) once the monitor
has collected it from whichever backend finished the work.

Run:  ``python -m comfyui_parallelanything_tpu.fleet.router \
          --backends http://h1:8188,http://h2:8188 [--port 8187]``
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import faults
from ..utils import retry as retry_mod
from ..utils import slo, tracing
from ..utils.logging import get_logger
from ..utils.metrics import registry
from . import roles as roles_mod
from .journal import JournalFollower, PromptJournal
from .registry import FleetRegistry, stable_hash
from .roles import RolePools
from .scoreboard import Scoreboard, merge_metrics

log = get_logger()

FLEET_HEALTH_SCHEMA = "pa-fleet-health/v1"


class StandbyRouter(RuntimeError):
    """This router is a standby (the primary holds the lease): submissions
    are refused with 503 — clients fail over to the primary, or wait for
    this router's takeover."""


class NoHealthyHost(RuntimeError):
    """No backend can take the prompt right now — surfaced as HTTP 503."""


class FleetSaturated(RuntimeError):
    """Every healthy backend refused with backpressure — HTTP 429."""


class BackendRejected(RuntimeError):
    """A backend refused the prompt with a non-retryable client error (400
    bad graph, …): the fault is the REQUEST, not the host — passed through
    to the client verbatim, never retried on siblings, never counted toward
    the CI-gated lost budget."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = int(code)


def model_key(graph: dict) -> str:
    """The placement key: the MODEL identity of a prompt graph, not the
    prompt itself. Loader-class nodes (``class_type`` containing "Loader")
    name the artifacts a host must have resident — their inputs (checkpoint
    path, clip pairing, …) are the key; seeds/steps/samplers deliberately
    are NOT, so every prompt against one model hashes to the same primary
    host. Graphs with no loader nodes fall back to the sorted class_type
    multiset (structure, not volatile inputs)."""
    loaders = []
    for nid in sorted(graph):
        spec = graph[nid] if isinstance(graph[nid], dict) else {}
        ct = str(spec.get("class_type", ""))
        if "Loader" in ct:
            loaders.append((ct, json.dumps(
                spec.get("inputs", {}), sort_keys=True, default=str
            )))
    if not loaders:
        loaders = sorted(
            str((graph[n] or {}).get("class_type", ""))
            for n in graph if isinstance(graph[n], dict)
        )
    return f"{stable_hash(json.dumps(loaders)):016x}"


@dataclasses.dataclass
class FleetPrompt:
    """One client prompt's fleet lifecycle: the submission is retained until
    the entry is collected, so the prompt survives its host."""

    pid: str                    # router-scoped id, stable across failovers
    graph: dict
    extra: dict | None
    key: str                    # model placement key
    number: int = 0
    host_id: str | None = None
    backend_pid: str | None = None
    attempts: int = 0           # dispatch tries (successful or not)
    failovers: int = 0          # times moved off a dead/unhealthy host
    # submitting → inflight → done (or → lost); failover resets to queued.
    # "submitting" (the initial state) is OWNED by the submit() call —
    # the monitor's queued-retry sweep must not see a half-submitted
    # prompt as retryable, or it double-dispatches it. A standby router
    # additionally holds journal SHADOWS ("shadow-submit" /
    # "shadow-inflight") that become live queued/inflight prompts at
    # takeover.
    status: str = "submitting"
    entry: dict | None = None
    submit_monotonic: float = dataclasses.field(default_factory=time.monotonic)
    trace_submit_us: float | None = None
    # Queued-retry backoff (utils/retry.py): the monitor re-dispatches a
    # queued prompt only once its window elapses — no hot-looping the whole
    # queue against a saturated/empty fleet every 50 ms sweep.
    retry_at: float = 0.0
    queue_retries: int = 0
    # Role-pool stage lifecycle (fleet/roles.py): ``plan`` is the carve
    # (host.carve_stages), set only when the fleet is disaggregated and the
    # graph carves; ``stage_idx`` names the stage the current dispatch owns;
    # ``stage_handles`` is the accumulated content-addressed lineage
    # (node id → stage-store key) and ``stage_hosts`` which hosts banked
    # those handles (their bases ride the next stage's pa_stage.sources).
    # A failover re-dispatches ONLY the current stage — completed stages
    # survive as handles, which is the whole point of the lineage.
    plan: dict | None = None
    stage_idx: int = 0
    stage_handles: dict = dataclasses.field(default_factory=dict)
    stage_hosts: list = dataclasses.field(default_factory=list)
    # Every successful dispatch hop, in order — {host, backend_pid, stage,
    # stage_idx, attempt}. This is the stitch index: GET /fleet/trace walks
    # it to pull each involved host's span export (failover means one stage
    # can appear twice, on two hosts — both hops are part of the story).
    hops: list = dataclasses.field(default_factory=list)


class FleetRouter:
    """Placement + admission + failover over a registry and a scoreboard.

    ``auto=True`` runs the monitor thread (health polls, heartbeat expiry,
    history collection, dead-host failover); ``auto=False`` exposes the same
    sweep as :meth:`poll_once` for deterministic tests."""

    def __init__(self, fleet_registry: FleetRegistry | None = None,
                 scoreboard: Scoreboard | None = None, *,
                 saturation_depth: int = 4, max_attempts: int = 4,
                 monitor_s: float = 0.2, hbm_watermark: float = 0.95,
                 http_timeout_s: float = 30.0, max_history: int = 4096,
                 journal: PromptJournal | None = None,
                 standby: bool = False, lease_ttl_s: float = 10.0,
                 follower: JournalFollower | None = None,
                 retry_policy: retry_mod.RetryPolicy | None = None,
                 rebalance_warm_s: float = 30.0,
                 auto: bool = True):
        self.registry = fleet_registry or FleetRegistry()
        self.scoreboard = scoreboard or Scoreboard()
        # Role pools (fleet/roles.py): per-stage consistent-hash rings over
        # the hosts advertising each role. With every host at the default
        # "all" the pools are the whole ring and placement below is
        # bitwise-identical to the single-pool router.
        self.roles = RolePools(self.registry, self.scoreboard)
        self.saturation_depth = int(saturation_depth)
        self.max_attempts = int(max_attempts)
        self.monitor_s = float(monitor_s)
        self.hbm_watermark = hbm_watermark
        self.http_timeout_s = float(http_timeout_s)
        # Resolved prompts beyond this budget are evicted oldest-first (the
        # graph + entry of every prompt ever served must not accumulate for
        # the router's lifetime); in-flight prompts are never evicted.
        self.max_history = int(max_history)
        # Router HA (fleet/journal.py): the ACTIVE router journals every
        # submit/dispatch/resolve and heartbeats the lease; a STANDBY tails
        # the journal (shared path, or HTTP via ``follower``), serves
        # /history from the shadows, and takes over — replaying every
        # unresolved prompt through normal placement — when the primary's
        # lease goes stale (or, in HTTP mode, its journal feed dies).
        self.journal = journal
        self.active = not standby
        if not self.active and self.journal is None:
            raise ValueError(
                "a standby router requires a journal (what would it replay?)"
            )
        self.lease_ttl_s = float(lease_ttl_s)
        self.follower = follower
        self._follow_failures = 0
        self._journal_offset = 0
        # A standby younger than one lease TTL has no basis to judge the
        # primary dead (it may simply not have observed a lease yet — e.g.
        # both routers racing up): minimum dwell before any takeover.
        self._standby_since = time.monotonic()
        # Queued-retry backoff shape (utils/retry.py).
        self.retry_policy = retry_policy or retry_mod.RetryPolicy(
            max_attempts=1_000_000, base_s=max(0.05, self.monitor_s),
            cap_s=5.0, jitter=0.25,
        )
        self.router_id = f"router-{uuid.uuid4().hex[:8]}"
        # Ring-change warm dwell (ROADMAP fleet remainder, round 15): for
        # ``rebalance_warm_s`` after a join/leave reshuffle, placement runs
        # prefer_warm — keys whose ring primary just moved to a cold joiner
        # re-home to warm siblings first instead of paying the compile +
        # weight staging on the new primary; the dwell ends once the
        # joiner has had time to warm organically (failover/replay keeps
        # its own unconditional prefer_warm, as before).
        self.rebalance_warm_s = float(rebalance_warm_s)
        self._ring_changed_until = 0.0
        self.prompts: dict[str, FleetPrompt] = {}  # guarded-by: _lock
        self._inflight: dict[str, int] = {}   # host_id → router-side count — guarded-by: _lock
        # monotonic stamp of the last router-side inflight DECREASE per
        # host: a health poll older than this carries a provably stale-high
        # inflight count (see Scoreboard.saturated include_polled).
        self._last_drop: dict[str, float] = {}  # guarded-by: _lock
        self._counter = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.active and self.journal is not None:
            self.journal.write_lease(self.router_id)
        # Router-side history sampler: the router's own ring is what makes
        # heartbeat staleness watchable (pa_fleet_host_health_age_s lives
        # in THIS registry). PA_HISTORY_BYTES=0 keeps this a no-op; the
        # cadence runs on its own daemon thread, never the dispatch path.
        self._history_sampler = None
        if auto:
            try:
                from ..utils import timeseries
                if timeseries.enabled():
                    self._history_sampler = timeseries.HistorySampler(
                        host=self.router_id
                    ).start()
            except Exception:  # pragma: no cover - best-effort telemetry
                self._history_sampler = None
        if auto:
            self._thread = threading.Thread(
                target=self._loop, name="pa-fleet-monitor", daemon=True
            )
            self._thread.start()

    # -- backend HTTP -------------------------------------------------------

    @staticmethod
    def _partition_check(base: str) -> None:
        """Fault site (utils/faults.py ``network-partition``): the
        router→backend half of a partition — every outbound call to the
        matched base raises the same refused-socket OSError a real severed
        link produces, while the backend itself stays healthy (its half is
        the HeartbeatClient's skipped beat). The dispatch/collect paths then
        exercise their real failure handling: scoreboard failure counts,
        ring walk-on, dead-host failover."""
        if faults.check("network-partition", key=f"router->{base}") is not None:
            raise OSError(f"injected network partition: router->{base}")

    def _post(self, base: str, path: str, payload: dict,
              timeout: float | None = None) -> dict:
        self._partition_check(base)
        req = urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=timeout or self.http_timeout_s
        ) as r:
            return json.loads(r.read())

    def _get(self, base: str, path: str, timeout: float | None = None):
        self._partition_check(base)
        with urllib.request.urlopen(
            base + path, timeout=timeout or self.http_timeout_s
        ) as r:
            return json.loads(r.read())

    # -- placement ----------------------------------------------------------

    def _router_inflight(self, host_id: str) -> int:
        with self._lock:
            return self._inflight.get(host_id, 0)

    def _release(self, host_id: str) -> None:
        with self._lock:
            self._inflight[host_id] = max(
                0, self._inflight.get(host_id, 0) - 1
            )
            self._last_drop[host_id] = time.monotonic()

    def note_ring_change(self) -> None:
        """A join/leave reshuffled the ring: open the prefer-warm dwell
        window (see ``rebalance_warm_s``)."""
        with self._lock:
            self._ring_changed_until = (
                time.monotonic() + self.rebalance_warm_s
            )

    def _ring_recently_changed(self) -> bool:
        with self._lock:
            return time.monotonic() < self._ring_changed_until

    def _polled_fresh(self, host_id: str) -> bool:
        """Is the scoreboard's last poll newer than this router's own last
        completion/rollback for the host? If not, its inflight count is
        stale-high and must not gate admission."""
        polled = self.scoreboard.last_ok(host_id)
        return (polled is not None
                and polled >= self._last_drop.get(host_id, 0.0))

    def place(self, key: str, exclude=(),
              prefer_warm: bool = False,
              role: str | None = None) -> tuple[str, str, bool]:
        """(host_id, base, spilled) for a model key: the first accepting
        host in ring order that is not saturated; if every accepting host is
        saturated, the least-loaded one (bounded queueing beats a 503 while
        capacity exists). Raises NoHealthyHost when nothing is accepting.

        ``prefer_warm`` (the failover/replay path): hosts advertising the
        key in their pa-health/v3 ``warm_keys`` are tried first, ring order
        within each tier — replaying a dead host's prompt on a warm sibling
        skips the compile + weight staging a cold primary would pay
        (ROADMAP fleet item 3). Fresh traffic keeps pure ring order: warm
        affinity is already where the ring points.

        ``role`` (the disaggregated path, fleet/roles.py): ring order comes
        from that role's POOL ring — the hosts advertising the stage's role
        (plus ``all`` generalists) — so an encode stage never lands on a
        heavy denoise chip and per-role scaling is purely membership. With
        ``role=None`` (every single-pool deployment) this is the registry
        ring verbatim."""
        seq = (self.roles.sequence(role, key) if role is not None
               else self.registry.sequence(key))
        candidates = [
            h for h in seq
            if h not in exclude and self.scoreboard.accepting(h)
        ]
        if not candidates and role is not None:
            # The role pool exists but no member is accepting (e.g. the only
            # decode host died mid-stage). Degrade to the global ring: any
            # healthy host runs the stage closure bitwise (fold_in replay
            # contract), so losing a whole tier costs locality, never
            # prompts.
            seq = self.registry.sequence(key)
            candidates = [
                h for h in seq
                if h not in exclude and self.scoreboard.accepting(h)
            ]
            if candidates:
                registry.counter(
                    "pa_role_pool_degraded_total", labels={"role": role},
                    help="stage placements that fell back to the global "
                         "ring because the role pool had no accepting host",
                )
        if not candidates:
            raise NoHealthyHost(
                f"no accepting backend for key {key} "
                f"(ring: {len(seq)} hosts, excluded: {sorted(exclude)})"
            )
        primary = seq[0]
        if prefer_warm:
            warm = [h for h in candidates if self.scoreboard.warm(h, key)]
            if warm:
                candidates = warm + [h for h in candidates if h not in warm]
        for h in candidates:
            if not self.scoreboard.saturated(
                h, extra_inflight=self._router_inflight(h),
                depth=self.saturation_depth,
                hbm_watermark=self.hbm_watermark,
                include_polled=self._polled_fresh(h),
            ):
                return h, self.registry.base_of(h), h != primary
        best = min(
            candidates,
            key=lambda h: self._router_inflight(h),
        )
        return best, self.registry.base_of(best), best != primary

    # -- submission / dispatch ---------------------------------------------

    def _journal_resolve(self, fp: FleetPrompt, status: str | None = None) -> None:
        if self.journal is not None:
            self.journal.append("resolve", fp.pid,
                                status=status or fp.status, entry=fp.entry)

    @staticmethod
    def _carve(graph: dict) -> dict | None:
        """The stage plan (host.carve_stages) for a graph, or None when it
        doesn't carve — the single-dispatch fallback. Imported lazily: the
        router stays a thin stdlib process until a disaggregated fleet
        actually needs the carve, and any import/carve failure degrades to
        whole-graph dispatch, never an error (the backend re-derives the
        same carve from the same graph, so both sides always agree)."""
        try:
            from ..host import carve_stages
            return carve_stages(graph)
        except Exception:  # noqa: BLE001 — degrade to single dispatch
            return None

    @staticmethod
    def _stage_of(fp: FleetPrompt) -> dict | None:
        """The plan entry the prompt's CURRENT dispatch owns, or None for
        unstaged prompts."""
        if fp.plan is None:
            return None
        stages = fp.plan.get("stages") or []
        if 0 <= fp.stage_idx < len(stages):
            return stages[fp.stage_idx]
        return None

    def submit(self, graph: dict, extra: dict | None = None) -> tuple[str, int]:
        """Admit one prompt into the fleet; returns (router prompt_id,
        submission number). Raises NoHealthyHost / FleetSaturated when no
        backend can take it (explicit backpressure, the 503/429 surface),
        StandbyRouter when this router doesn't hold the lease."""
        if not self.active:
            raise StandbyRouter(
                f"router {self.router_id} is a standby — the primary holds "
                f"the lease; retry there (or here after takeover)"
            )
        pid = uuid.uuid4().hex
        with self._lock:
            self._counter += 1
            number = self._counter
        fp = FleetPrompt(
            pid=pid, graph=graph, extra=extra, key=model_key(graph),
            number=number,
            trace_submit_us=tracing.now_us() if tracing.on() else None,
        )
        # Disaggregated fleets carve the graph into role stages at
        # admission; a graph that doesn't carve (or a single-pool fleet)
        # dispatches whole — the bitwise-unchanged default.
        if self.roles.disaggregated():
            fp.plan = self._carve(graph)
        with self._lock:
            self.prompts[pid] = fp
        # Journal BEFORE the dispatch: a router that dies mid-placement must
        # still leave the submission recoverable (the client has no pid yet
        # on that path, but the standby resolving an orphan beats losing a
        # submission whose POST raced the crash).
        if self.journal is not None:
            self.journal.append("submit", pid, graph=graph, extra=extra,
                                key=fp.key, number=number)
        try:
            self._dispatch(fp)
        except (NoHealthyHost, FleetSaturated, BackendRejected):
            with self._lock:
                self.prompts.pop(pid, None)
            # The client got an error for this submission — the journal must
            # say so, or a standby would faithfully replay a prompt its
            # client believes was refused.
            self._journal_resolve(fp, status="rejected")
            raise
        return pid, number

    def _prune_history(self) -> None:  # palint: holds _lock
        """Evict the oldest RESOLVED prompts beyond the history budget
        (caller holds the lock; dicts iterate in insertion = submit order)."""
        excess = len(self.prompts) - self.max_history
        if excess <= 0:
            return
        for pid in [p for p, fp in self.prompts.items()
                    if fp.status in ("done", "lost")][:excess]:
            del self.prompts[pid]

    def _dispatch(self, fp: FleetPrompt, exclude: set | None = None,
                  prefer_warm: bool = False) -> None:
        """Place and forward one prompt, walking the ring past refusing or
        unreachable hosts. On success the prompt is ``inflight``; exhausting
        every candidate raises (submit path) — failover callers catch and
        leave the prompt ``queued`` for the next monitor sweep."""
        exclude = set(exclude or ())
        # Ring-change dwell: fresh traffic ALSO prefers warm siblings while
        # a join/leave reshuffle is settling — a key re-homed to a cold
        # joiner goes where its programs are still resident instead.
        prefer_warm = prefer_warm or self._ring_recently_changed()
        # Staged prompts (fleet/roles.py) dispatch their CURRENT stage to
        # that stage's role pool: the full graph travels (the backend
        # re-derives the same carve — both sides always agree on the
        # boundary), plus ``pa_stage`` naming the stage, the lineage handles
        # covering its needs, and the bases holding those handles.
        stage = self._stage_of(fp)
        role = str(stage["stage"]) if stage is not None else None
        saw_backpressure = False
        while True:
            if fp.attempts >= self.max_attempts:
                self._mark_lost(fp)
                return
            # Place AND reserve under one lock hold: two simultaneous
            # submits must not both read a host as free and stack onto it
            # while a sibling sits idle (the reservation is rolled back if
            # the POST fails).
            with self._lock:
                try:
                    host, base, spilled = self.place(
                        fp.key, exclude=exclude, prefer_warm=prefer_warm,
                        role=role,
                    )
                except NoHealthyHost:
                    if saw_backpressure:
                        # Everything healthy refused with 429/503: the fleet
                        # is saturated, not dead — the client should back off.
                        raise FleetSaturated(
                            "every healthy backend refused with backpressure"
                        ) from None
                    raise
                if base is not None:
                    self._inflight[host] = self._inflight.get(host, 0) + 1
            if base is None:
                exclude.add(host)
                continue
            fp.attempts += 1
            extra = dict(fp.extra or {})
            # The cross-hop correlation: the backend stamps this origin id
            # onto its own prompt span, so one Perfetto export holds the
            # router-side fleet-prompt span AND the backend-side prompt
            # timeline joined by origin_prompt_id.
            extra["fleet"] = {"origin": fp.pid, "router": self.router_id}
            # Distributed-trace propagation (W3C traceparent shape): the
            # router prompt_id IS the trace_id lineage — every hop of this
            # prompt (stage hand-offs, failover re-dispatches, post-takeover
            # replays) carries the SAME trace_id, so the /fleet/trace
            # stitcher joins all hosts' spans under one id. Injected when
            # the router traces, or when the client sampled this prompt for
            # capture (loadgen --trace-sample sets pa_trace_sampled).
            if tracing.on() or extra.get("pa_trace_sampled"):
                extra["fleet"]["traceparent"] = tracing.format_traceparent(
                    fp.pid, sampled=True
                )
            if stage is not None:
                with self._lock:
                    # The FULL accumulated lineage, not just this stage's
                    # declared needs: a later stage's closure names every
                    # upstream node, and any resolved boundary inside it
                    # (the encode output two stages back) short-circuits
                    # that node's whole prefix on the executing host —
                    # without it a decode host re-runs the encoder class.
                    handles = dict(fp.stage_handles)
                    sources = []
                    for hid in fp.stage_hosts:
                        b = self.registry.base_of(hid)
                        if b and b not in sources:
                            sources.append(b)
                extra["pa_stage"] = {"stage": str(stage["stage"]),
                                     "handles": handles, "sources": sources}
            t0_us = tracing.now_us() if tracing.on() else 0.0
            try:
                resp = self._post(
                    base, "/prompt",
                    {"prompt": fp.graph, "extra_data": extra},
                )
            except urllib.error.HTTPError as e:
                self._release(host)
                if e.code in (429, 503):
                    # Alive but refusing with backpressure (429 bounded
                    # queue, 503 draining): not a health failure — exclude,
                    # walk on.
                    saw_backpressure = True
                    exclude.add(host)
                    continue
                if e.code >= 500:
                    # Server-side failure (500/502/504 — a half-dead backend
                    # whose handler errors while its health endpoint still
                    # answers): the HOST is at fault, exactly like a refused
                    # socket — feed the scoreboard's failure counter and
                    # walk the ring. (Chaos finding, round 14: this used to
                    # be classified as a client error and surfaced to the
                    # submitter — one injected 5xx cost a prompt.)
                    self.scoreboard.record_failure(
                        host, base, f"dispatch: HTTP {e.code}"
                    )
                    exclude.add(host)
                    continue
                # Non-retryable client error (400 bad graph, …): the
                # REQUEST is at fault, not the host — retrying it on
                # siblings would burn the retry budget into the
                # CI-gated lost counter for a client mistake.
                try:
                    detail = json.loads(e.read() or b"{}").get("error")
                except Exception:  # noqa: BLE001 — body is best-effort
                    detail = None
                raise BackendRejected(
                    e.code, detail or f"backend refused: HTTP {e.code}"
                ) from e
            except OSError as e:
                self.scoreboard.record_failure(host, base, f"dispatch: {e}")
                self._release(host)
                exclude.add(host)
                continue
            with self._lock:
                fp.host_id = host
                fp.backend_pid = resp.get("prompt_id")
                fp.status = "inflight"
                fp.hops.append({
                    "host": host, "backend_pid": fp.backend_pid,
                    "stage": role, "stage_idx":
                        fp.stage_idx if stage is not None else None,
                    "attempt": fp.attempts,
                })
            if self.journal is not None:
                if stage is not None and fp.stage_idx > 0:
                    # Ownership moved to a later stage's pool host: the
                    # lineage record a standby resumes from (journal.py).
                    self.journal.append("stage_dispatch", fp.pid, host=host,
                                        backend_pid=fp.backend_pid,
                                        attempt=fp.attempts,
                                        stage=str(stage["stage"]),
                                        stage_idx=fp.stage_idx)
                elif stage is not None:
                    self.journal.append("dispatch", fp.pid, host=host,
                                        backend_pid=fp.backend_pid,
                                        attempt=fp.attempts,
                                        stage=str(stage["stage"]),
                                        stage_idx=fp.stage_idx)
                else:
                    self.journal.append("dispatch", fp.pid, host=host,
                                        backend_pid=fp.backend_pid,
                                        attempt=fp.attempts)
            registry.counter("pa_fleet_dispatch_total",
                             labels={"host": host},
                             help="prompts forwarded per backend")
            if role is not None:
                registry.counter(
                    "pa_role_dispatch_total",
                    labels={"role": role, "host": host},
                    help="stage dispatches per role pool (fleet/roles.py)",
                )
            if spilled:
                registry.counter(
                    "pa_fleet_spill_total", labels={"host": host},
                    help="prompts placed off their warm-affinity primary",
                )
            if tracing.on():
                # role/pool attrs (round 21 fix): `role` is the dispatched
                # stage's tier ("all" for unstaged traffic), `pool` the
                # serving host's DECLARED membership — they differ when a
                # generalist host absorbs a stage, which is exactly the
                # per-tier latency question the labels make filterable.
                dur_us = tracing.now_us() - t0_us
                pool = self.roles.role_of(host)
                tracing.record(
                    "fleet-hop", t0_us, dur_us,
                    cat="fleet", prompt_id=fp.pid, host=host,
                    backend_pid=fp.backend_pid, attempt=fp.attempts,
                    spilled=spilled, role=role or "all", pool=pool,
                    trace_id=fp.pid,
                )
                if stage is not None:
                    tracing.record(
                        "stage-dispatch", t0_us, dur_us,
                        cat="fleet", prompt_id=fp.pid, host=host,
                        stage=role, stage_idx=fp.stage_idx,
                        role=role or "all", pool=pool, trace_id=fp.pid,
                    )
            return

    def _mark_lost(self, fp: FleetPrompt) -> None:
        """Retry budget exhausted — the only way the fleet ever gives up on
        a prompt, and the counter CI gates on staying zero."""
        with self._lock:
            fp.status = "lost"
            fp.entry = {
                "status": {
                    "status_str": "error", "completed": False,
                    "message": (
                        f"lost after {fp.attempts} dispatch attempts "
                        f"({fp.failovers} failovers)"
                    ),
                    "fleet": {"host_id": fp.host_id,
                              "attempts": fp.attempts,
                              "failovers": fp.failovers, "lost": True},
                },
                "outputs": {},
            }
        self._journal_resolve(fp)
        registry.counter("pa_fleet_prompts_lost_total",
                         help="prompts abandoned after the retry budget — "
                              "zero on a healthy fleet (CI-gated)")
        log.error("fleet prompt %s LOST after %d attempts",
                  fp.pid, fp.attempts)

    # -- completion / failover ---------------------------------------------

    def _complete(self, fp: FleetPrompt, entry: dict) -> None:
        with self._lock:
            if fp.status != "inflight":
                return
            fp.status = "done"
            entry = dict(entry)
            status = dict(entry.get("status") or {})
            status["fleet"] = {
                "host_id": fp.host_id, "attempts": fp.attempts,
                "failovers": fp.failovers,
            }
            entry["status"] = status
            fp.entry = entry
            if fp.host_id:
                self._inflight[fp.host_id] = max(
                    0, self._inflight.get(fp.host_id, 0) - 1
                )  # inline (holds the lock) — not _release
                self._last_drop[fp.host_id] = time.monotonic()
        self._journal_resolve(fp)
        registry.counter("pa_fleet_completed_total",
                         help="prompts whose history entry was collected")
        if tracing.on() and fp.trace_submit_us is not None:
            tracing.record(
                "fleet-prompt", fp.trace_submit_us,
                tracing.now_us() - fp.trace_submit_us, cat="fleet",
                prompt_id=fp.pid, host=fp.host_id, attempts=fp.attempts,
                failovers=fp.failovers, trace_id=fp.pid,
                outcome=(entry.get("status") or {}).get("status_str"),
            )
            # Snapshot the router-side spans into the completed-prompt
            # retention ring: /fleet/trace must still stitch this prompt
            # after the live rings wrap under later traffic.
            tracing.retain_prompt(fp.pid)

    def _stage_or_complete(self, fp: FleetPrompt, entry: dict) -> None:
        """Route a collected entry: a non-final STAGE result advances the
        lineage and dispatches the next stage; everything else — an
        unstaged prompt, the final stage, an errored stage, or an entry
        WITHOUT ``status.pa_stage`` (the backend fell back to whole-graph
        execution, so this entry already IS the prompt's result) —
        completes the prompt."""
        stage = self._stage_of(fp)
        if stage is None:
            return self._complete(fp, entry)
        status = entry.get("status") if isinstance(entry, dict) else None
        ps = status.get("pa_stage") if isinstance(status, dict) else None
        if (isinstance(ps, dict)
                and str(ps.get("stage")) != str(stage["stage"])):
            # The entry belongs to an ALREADY-RESOLVED earlier stage: a
            # takeover adopted this prompt between its stage_resolve and
            # the next stage_dispatch, so re-collecting the old owner's
            # history yields the banked stage again. The lineage already
            # holds those handles — claim the prompt and dispatch the
            # CURRENT stage instead of advancing past it (or, worse,
            # completing a decode-stage prompt with a denoise entry).
            with self._lock:
                if fp.status != "inflight":
                    return
                fp.stage_handles.update({
                    str(k): str(v)
                    for k, v in (ps.get("handles") or {}).items()
                })
                if fp.host_id:
                    if fp.host_id not in fp.stage_hosts:
                        fp.stage_hosts.append(fp.host_id)
                    self._inflight[fp.host_id] = max(
                        0, self._inflight.get(fp.host_id, 0) - 1
                    )  # inline (holds the lock) — not _release
                    self._last_drop[fp.host_id] = time.monotonic()
                fp.status = "submitting"
                fp.host_id = None
                fp.backend_pid = None
            self._dispatch_or_queue(fp)
            return
        final = fp.stage_idx >= len(fp.plan.get("stages") or ()) - 1
        if not isinstance(ps, dict) or final:
            return self._complete(fp, entry)
        if (status.get("status_str") == "error"
                or not status.get("completed", True)):
            # A failed stage fails the prompt — the error entry is the
            # client's answer; nothing downstream could run anyway.
            return self._complete(fp, entry)
        self._advance_stage(fp, entry, ps)

    def _advance_stage(self, fp: FleetPrompt, entry: dict, ps: dict) -> None:
        """Bank a completed stage's content-addressed handles into the
        lineage (journal ``stage_resolve``) and dispatch the next stage to
        its role pool. Concurrent collectors are safe: the first caller
        flips the prompt off ``inflight`` under the lock; later ones
        no-op."""
        with self._lock:
            if fp.status != "inflight":
                return
            stage_name = str(ps.get("stage") or "")
            done_idx = fp.stage_idx
            done_host = fp.host_id
            handles = {str(k): str(v)
                       for k, v in (ps.get("handles") or {}).items()}
            fp.stage_handles.update(handles)
            if done_host and done_host not in fp.stage_hosts:
                fp.stage_hosts.append(done_host)
            if done_host:
                self._inflight[done_host] = max(
                    0, self._inflight.get(done_host, 0) - 1
                )  # inline (holds the lock) — not _release
                self._last_drop[done_host] = time.monotonic()
            # Claimed by THIS caller for the next hop (same rule as
            # failover_host: the queued-retry sweep must not double-dispatch
            # a prompt another thread is already advancing).
            fp.status = "submitting"
            fp.stage_idx = done_idx + 1
            fp.host_id = None
            fp.backend_pid = None
        if self.journal is not None:
            self.journal.append("stage_resolve", fp.pid, stage=stage_name,
                                stage_idx=done_idx, host=done_host,
                                handles=handles)
        registry.counter("pa_role_stage_resolved_total",
                         labels={"role": stage_name or "?"},
                         help="stage results banked into the lineage "
                              "(fleet/roles.py)")
        self._dispatch_or_queue(fp)

    def failover_host(self, host_id: str, reason: str) -> int:
        """Move every in-flight prompt off a dead/unhealthy host: re-submit
        each to the next ring sibling. The replay runs from step 0 there;
        the fold_in RNG discipline makes its output bitwise-equal to an
        uninterrupted run, so the client sees only latency, never a
        different image. Returns how many prompts were moved."""
        with self._lock:
            victims = [
                fp for fp in self.prompts.values()
                if fp.status == "inflight" and fp.host_id == host_id
            ]
            for fp in victims:
                # Claimed by THIS caller ("submitting") — the monitor's
                # queued-retry sweep must not concurrently dispatch a prompt
                # another thread is already re-dispatching. It becomes
                # "queued" (retryable) only if this dispatch finds no home.
                fp.status = "submitting"
                fp.failovers += 1
                fp.host_id = None
                fp.backend_pid = None
            self._inflight[host_id] = 0
            self._last_drop[host_id] = time.monotonic()
        if not victims:
            return 0
        registry.counter("pa_fleet_failover_total", inc=float(len(victims)),
                         labels={"host": host_id},
                         help="in-flight prompts moved off a failed host")
        log.warning("fleet failover: %d prompt(s) off %s (%s)",
                    len(victims), host_id, reason)
        for fp in victims:
            self._dispatch_or_queue(fp, exclude={host_id}, prefer_warm=True)
        return len(victims)

    def _dispatch_or_queue(self, fp: FleetPrompt, exclude=None,
                           prefer_warm: bool = False) -> None:
        """Re-dispatch a claimed prompt; park it ``queued`` (monitor retries,
        on the retry policy's backoff) when no backend can take it now, and
        resolve it as an error entry on a non-retryable backend rejection
        (no client thread is waiting on a failover path, so the rejection
        lands in its history entry). Failover/replay callers pass
        ``prefer_warm`` — a warm sibling beats a cold primary for a prompt
        that must restart from step 0 anyway."""
        try:
            self._dispatch(fp, exclude=exclude, prefer_warm=prefer_warm)
        except (NoHealthyHost, FleetSaturated):
            with self._lock:
                if fp.status == "submitting":
                    fp.status = "queued"
                    fp.retry_at = time.monotonic() + self.retry_policy.backoff_s(
                        fp.queue_retries, key=fp.pid
                    )
                    fp.queue_retries += 1
        except BackendRejected as e:
            with self._lock:
                fp.status = "done"
                fp.entry = {
                    "status": {
                        "status_str": "error", "completed": False,
                        "message": str(e),
                        "fleet": {"host_id": fp.host_id,
                                  "attempts": fp.attempts,
                                  "failovers": fp.failovers},
                    },
                    "outputs": {},
                }
            self._journal_resolve(fp)

    # -- the monitor sweep --------------------------------------------------

    def poll_once(self) -> None:
        """One monitor sweep. Active: heartbeat the lease, expire silent
        hosts, poll due health, fail over the dead, collect finished
        histories, retry due queued prompts. Standby: tail the journal into
        shadows and take over when the primary is provably dead."""
        if not self.active:
            self._standby_sweep()
            return
        if self.journal is not None:
            # Ownership re-check BEFORE refreshing: if another router holds
            # a FRESH lease (a standby declared us dead — e.g. one of our
            # sweeps stalled on a blackholed backend past the TTL), step
            # down instead of fighting it. A false takeover then costs one
            # orderly demotion, never a permanent dual-active split brain
            # (both dispatching the same prompts, both appending the
            # journal). The demoted router keeps its prompt table and
            # becomes a live standby for the new primary.
            lease = self.journal.read_lease()
            if (lease is not None
                    and lease.get("router_id") != self.router_id
                    and not self.journal.lease_stale(self.lease_ttl_s)):
                self.active = False
                self._standby_since = time.monotonic()
                self._journal_offset = 0  # re-fold the journal as shadows
                registry.counter("pa_fleet_stepdown_total",
                                 help="active routers that yielded to a "
                                      "fresher lease holder")
                log.warning(
                    "fleet router %s STEPPED DOWN: %s holds a fresh lease",
                    self.router_id, lease.get("router_id"),
                )
                self._standby_sweep()
                return
            self.journal.write_lease(self.router_id)
        expired = self.registry.expire()
        if expired:
            self.note_ring_change()  # leave reshuffle: prefer-warm dwell
        for hid in expired:
            self.failover_host(hid, "heartbeat expired")
        hosts = {hid: info.base for hid, info in self.registry.hosts().items()}
        self.scoreboard.poll_due(hosts)
        for hid in hosts:
            if self.scoreboard.dead(hid):
                self.failover_host(hid, "health polls failing")
        # Adopted-after-takeover prompts can reference a host this router
        # never saw register (it heartbeat only the dead primary): a host
        # that isn't in the ring can never be collected from — fail its
        # prompts over to ring members.
        with self._lock:
            orphaned = {
                fp.host_id for fp in self.prompts.values()
                if fp.status == "inflight" and fp.host_id
                and fp.host_id not in hosts
            }
        for hid in orphaned:
            self.failover_host(hid, "host not in the ring")
        self._collect_histories()
        with self._lock:
            now = time.monotonic()
            queued = [fp for fp in self.prompts.values()
                      if fp.status == "queued" and fp.retry_at <= now]
            for fp in queued:
                fp.status = "submitting"  # claimed by this sweep
            self._prune_history()
        for fp in queued:
            # A queued prompt that has already failed over restarts from
            # step 0 wherever it lands — warm siblings first.
            self._dispatch_or_queue(fp, prefer_warm=fp.failovers > 0)

    # -- standby / takeover (fleet/journal.py) -------------------------------

    def _tail_shadow(self) -> None:
        """Fold any new journal records into shadow prompts (standby only).
        Only complete lines are consumed; a torn tail stays unread until the
        writer finishes it."""
        if self.follower is not None:
            ok = self.follower.poll() or not self.follower.unreachable
            self._follow_failures = 0 if ok else self._follow_failures + 1
        path = self.journal.path
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size <= self._journal_offset:
            return
        with open(path, "rb") as f:
            f.seek(self._journal_offset)
            data = f.read(size - self._journal_offset)
        last_nl = data.rfind(b"\n")
        if last_nl < 0:
            return
        self._journal_offset += last_nl + 1
        for raw in data[: last_nl + 1].splitlines():
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("pid"):
                self._apply_shadow(rec)

    def _apply_shadow(self, rec: dict) -> None:
        ev, pid = rec.get("ev"), rec["pid"]
        with self._lock:
            fp = self.prompts.get(pid)
            if ev == "submit":
                self.prompts[pid] = FleetPrompt(
                    pid=pid, graph=rec.get("graph") or {},
                    extra=rec.get("extra"),
                    key=rec.get("key") or model_key(rec.get("graph") or {}),
                    number=int(rec.get("number") or 0),
                    status="shadow-submit",
                )
            elif ev in ("dispatch", "stage_dispatch") and fp is not None:
                fp.status = "shadow-inflight"
                fp.host_id = rec.get("host")
                fp.backend_pid = rec.get("backend_pid")
                fp.attempts = int(rec.get("attempt") or fp.attempts)
                if rec.get("stage_idx") is not None:
                    fp.stage_idx = int(rec["stage_idx"])
            elif ev == "stage_resolve" and fp is not None:
                # The lineage a takeover resumes from: handles for every
                # completed stage, and which host banked them (its base
                # rides the next dispatch's pa_stage.sources).
                fp.stage_handles.update({
                    str(k): str(v)
                    for k, v in (rec.get("handles") or {}).items()
                })
                host = rec.get("host")
                if host and host not in fp.stage_hosts:
                    fp.stage_hosts.append(host)
                if rec.get("stage_idx") is not None:
                    fp.stage_idx = int(rec["stage_idx"]) + 1
            elif ev == "resolve" and fp is not None:
                entry = rec.get("entry")
                if rec.get("status") == "rejected" or entry is None:
                    # The primary told ITS client no (or resolved without an
                    # entry): nothing to serve, nothing to replay.
                    self.prompts.pop(pid, None)
                else:
                    fp.status = "lost" if rec.get("status") == "lost" else "done"
                    fp.entry = entry

    def _primary_dead(self) -> bool:
        if time.monotonic() - self._standby_since < self.lease_ttl_s:
            return False  # minimum dwell — see __init__
        if self.follower is not None:
            # HTTP mode: the standby cannot read the primary's lease file —
            # the journal feed dying for fail_after-equivalent polls IS the
            # death signal.
            return self._follow_failures >= 3
        return self.journal.lease_stale(self.lease_ttl_s,
                                        holder_not=self.router_id)

    def _standby_sweep(self) -> None:
        self._tail_shadow()
        with self._lock:
            # Resolved shadows obey the same history budget as the active
            # router's table — a standby mirroring a busy primary for weeks
            # must not hold every prompt's graph + entry forever.
            self._prune_history()
        if self._primary_dead():
            self.takeover()

    def takeover(self) -> int:
        """Assume the lease: shadows become live prompts — resolved ones
        serve /history as-is; dispatched ones go back to ``inflight`` (the
        normal monitor collects them from live backends, or fails them over
        off dead ones — replay-from-0 on a warm sibling, bitwise-equal by
        the fold_in contract); submitted-only ones queue for placement.
        Returns how many unresolved prompts were adopted."""
        self._tail_shadow()  # drain whatever the primary managed to write
        with self._lock:
            if self.active:
                return 0
            self.active = True
            adopted = 0
            max_number = self._counter
            for fp in self.prompts.values():
                if fp.status == "shadow-inflight":
                    fp.status = "inflight"
                    if fp.host_id:
                        self._inflight[fp.host_id] = (
                            self._inflight.get(fp.host_id, 0) + 1
                        )
                    adopted += 1
                elif fp.status == "shadow-submit":
                    fp.status = "queued"
                    adopted += 1
                else:
                    max_number = max(max_number, fp.number)
                    continue
                # A shadow with stage lineage needs its plan back (the
                # journal carries handles, not the carve — the carve is
                # deterministic in the graph). _carve returning None
                # degrades to whole-graph re-dispatch: still bitwise, just
                # not disaggregated.
                if (fp.stage_idx or fp.stage_handles) and fp.graph:
                    fp.plan = self._carve(fp.graph)
                    if fp.plan is None:
                        fp.stage_idx = 0
                        fp.stage_handles = {}
                elif fp.graph and self.roles.disaggregated():
                    fp.plan = self._carve(fp.graph)
                max_number = max(max_number, fp.number)
            # Submission numbers keep ascending across the failover.
            self._counter = max_number
        if self.journal is not None:
            self.journal.write_lease(self.router_id)
            self.journal.append("takeover", "-", router_id=self.router_id,
                                adopted=adopted)
        registry.counter("pa_fleet_takeover_total",
                         help="standby routers that assumed the lease")
        log.warning("fleet router %s TOOK OVER (%d unresolved prompt(s) "
                    "adopted)", self.router_id, adopted)
        return adopted

    def _collect_one(self, fp: FleetPrompt,
                     timeout: float | None = None) -> None:
        """Try to fetch one in-flight prompt's entry from its owner. Called
        from the monitor sweep AND inline from ``GET /history/{pid}`` — a
        client polling the router must see completion at its own poll
        cadence, not the monitor's (whose sweep also pays for health polls).
        Concurrent collectors are safe: ``_complete`` no-ops unless the
        prompt is still inflight."""
        if fp.status != "inflight" or fp.backend_pid is None:
            return
        base = self.registry.base_of(fp.host_id or "")
        if base is None:
            return
        try:
            hist = self._get(base, f"/history/{fp.backend_pid}",
                             timeout=timeout or self.http_timeout_s)
        except urllib.error.HTTPError:
            return
        except OSError as e:
            self.scoreboard.record_failure(fp.host_id, base, f"history: {e}")
            return
        entry = hist.get(fp.backend_pid)
        if entry:
            self._stage_or_complete(fp, entry)

    def _collect_histories(self) -> None:
        with self._lock:
            inflight = [fp for fp in self.prompts.values()
                        if fp.status == "inflight"]
        for fp in inflight:
            # Short per-collection timeout, and skip hosts already in
            # failure backoff: the monitor owns heartbeat expiry and
            # dead-host failover — one half-dead backend blocking a 30s
            # socket read per inflight prompt would stall the whole sweep
            # for minutes. (Clients' inline collects keep their own, longer
            # timeout.)
            if self.scoreboard.in_backoff(fp.host_id or ""):
                continue
            self._collect_one(fp, timeout=min(5.0, self.http_timeout_s))

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the monitor must survive
                pass
            self._stop.wait(self.monitor_s)

    # -- operations ---------------------------------------------------------

    def drain(self, host_id: str) -> dict:
        """Ask one backend to drain (stop seating, finish lanes) and stop
        placing there immediately — the host leaves the ring when its
        heartbeats stop (or via /fleet/leave)."""
        base = self.registry.base_of(host_id)
        if base is None:
            raise KeyError(f"unknown host {host_id!r}")
        self.scoreboard.mark_draining(host_id)
        return self._post(base, "/drain", {})

    def leave(self, host_id: str) -> bool:
        """Explicit ring departure; in-flight prompts fail over."""
        removed = self.registry.remove(host_id)
        if removed:
            self.note_ring_change()  # leave reshuffle: prefer-warm dwell
            self.failover_host(host_id, "left the ring")
        return removed

    def interrupt(self) -> int:
        """Broadcast POST /interrupt to every live backend (best-effort) and
        drop queued prompts."""
        dropped = 0
        interrupted: list[FleetPrompt] = []
        with self._lock:
            for fp in self.prompts.values():
                if fp.status == "queued":
                    # Operator cancel, not a loss: "done" with an
                    # interrupted entry, so the CI-gated lost count stays an
                    # involuntary-failure signal.
                    fp.status = "done"
                    fp.entry = {
                        "status": {"status_str": "interrupted",
                                   "completed": False},
                        "outputs": {},
                    }
                    interrupted.append(fp)
                    dropped += 1
        for fp in interrupted:
            self._journal_resolve(fp)
        for hid, info in self.registry.hosts().items():
            try:
                resp = self._post(info.base, "/interrupt", {}, timeout=10)
                dropped += int(resp.get("dropped", 0))
            except (OSError, urllib.error.HTTPError):
                pass
        return dropped

    def history(self, pid: str | None = None) -> dict:
        with self._lock:
            if pid is None:
                return {p: fp.entry for p, fp in self.prompts.items()
                        if fp.entry is not None}
            fp = self.prompts.get(pid)
        if fp is None:
            return {}
        if fp.entry is None:
            self._collect_one(fp)  # poll-path completion (see _collect_one)
        with self._lock:
            return {pid: fp.entry} if fp.entry is not None else {}

    def stats(self) -> dict:
        with self._lock:
            by_status: dict[str, int] = {}
            for fp in self.prompts.values():
                by_status[fp.status] = by_status.get(fp.status, 0) + 1
            inflight = dict(self._inflight)
        return {"prompts": by_status, "router_inflight": inflight,
                "lost": by_status.get("lost", 0)}

    def stitch_trace(self, pid: str) -> dict:
        """ONE Perfetto/Chrome timeline for one prompt across every process
        it touched (``GET /fleet/trace?prompt_id=``): the router's own spans
        plus each dispatch hop's host-side ``GET /trace?prompt_id=
        <backend_pid>`` export, each process on its own host-labeled track
        (trace-event ``pid``), clock domains aligned on the tracers'
        wall-clock epoch anchors, and the prompt's journal lineage records
        (submit / stage_dispatch / stage_resolve / takeover) merged in as
        instant events on the router track. Every X event is stamped with
        ``trace_id = <router prompt_id>`` — the single id the whole
        distributed story nests under. A dead hop (its host left the ring,
        or its /trace fetch fails) degrades to ``ok: false`` in ``hosts``;
        the surviving tracks still stitch."""
        with self._lock:
            fp = self.prompts.get(pid)
            hops = [dict(h) for h in fp.hops] if fp is not None else []
        if fp is None:
            return {"schema": "pa-fleet-trace/v1", "trace_id": pid,
                    "error": f"unknown prompt {pid!r}"}
        docs: list[dict] = [{
            "host": self.router_id, "role": "router", "backend_pid": pid,
            "ok": True, "doc": tracing.export(prompt_id=pid),
        }]
        seen: set = set()
        for hop in hops:
            bpid = hop.get("backend_pid")
            key = (hop.get("host"), bpid)
            if not bpid or key in seen:
                continue
            seen.add(key)
            host = str(hop.get("host") or "")
            entry = {
                "host": host,
                "role": hop.get("stage") or self.roles.role_of(host),
                "backend_pid": bpid, "stage_idx": hop.get("stage_idx"),
                "ok": False, "doc": None,
            }
            base = self.registry.base_of(host)
            if base is not None:
                try:
                    entry["doc"] = self._get(
                        base, f"/trace?prompt_id={bpid}",
                        timeout=min(10.0, self.http_timeout_s),
                    )
                    entry["ok"] = True
                except (OSError, ValueError, urllib.error.HTTPError):
                    pass
            docs.append(entry)
        # Clock-domain alignment: each process's trace-event ts is relative
        # to its OWN monotonic epoch; the wall-clock anchor taken at the
        # same instant maps them all onto the earliest anchor's timeline
        # (NTP-level skew is the error bar — ms against multi-ms spans).
        walls = [d["doc"]["epoch_wall_s"] for d in docs
                 if d.get("doc")
                 and isinstance(d["doc"].get("epoch_wall_s"), (int, float))]
        base_wall = min(walls) if walls else None
        meta: list[dict] = []
        events: list[dict] = []
        hosts_out: list[dict] = []
        for track, d in enumerate(docs):
            hosts_out.append({
                "pid": track, "host": d["host"], "role": d["role"],
                "backend_pid": d["backend_pid"], "ok": d["ok"],
                **({"stage_idx": d["stage_idx"]}
                   if d.get("stage_idx") is not None else {}),
            })
            doc = d.get("doc")
            if not doc:
                continue
            wall = doc.get("epoch_wall_s")
            shift_us = (
                (wall - base_wall) * 1e6
                if base_wall is not None and isinstance(wall, (int, float))
                else 0.0
            )
            meta.append({
                "ph": "M", "name": "process_name", "pid": track,
                "args": {"name": f"{d['host']} [{d['role']}]"},
            })
            for ev in doc.get("traceEvents") or []:
                ph = ev.get("ph")
                if ph == "M":
                    if ev.get("name") == "thread_name":
                        meta.append({**ev, "pid": track})
                    continue
                if ph != "X":
                    continue
                args = dict(ev.get("args") or {})
                args["trace_id"] = pid
                # Track identity fills in what the recording site didn't
                # know (setdefault: a fleet-hop span's own `host` attr —
                # the dispatched backend — must survive).
                args.setdefault("host", d["host"])
                args.setdefault("role", d["role"])
                events.append({
                    **ev, "pid": track,
                    "ts": round(ev.get("ts", 0.0) + shift_us, 3),
                    "args": args,
                })
        # Journal lineage as instant events on the router track: the stage
        # hand-off story (who banked which handles when, takeovers included)
        # interleaved with the spans it explains.
        if self.journal is not None and base_wall is not None:
            try:
                for rec in PromptJournal.iter_records(self.journal.path):
                    if rec.get("pid") != pid:
                        continue
                    ts = rec.get("ts")
                    if not isinstance(ts, (int, float)):
                        continue
                    events.append({
                        "ph": "i", "name": f"journal:{rec.get('ev')}",
                        "cat": "fleet", "s": "p", "pid": 0, "tid": 0,
                        "ts": round((ts - base_wall) * 1e6, 3),
                        "args": {
                            k: v for k, v in rec.items()
                            if k not in ("graph", "extra") and k != "pid"
                        } | {"trace_id": pid},
                    })
            except OSError:
                pass
        events.sort(key=lambda e: (e["pid"], e.get("tid", 0), e["ts"]))
        return {
            "schema": "pa-fleet-trace/v1",
            "trace_id": pid,
            "router_id": self.router_id,
            "enabled": tracing.on(),
            "displayTimeUnit": "ms",
            "epoch_wall_s": base_wall,
            "hosts": hosts_out,
            "traceEvents": meta + events,
        }

    def roles_view(self) -> dict:
        """The role-pool picture for ``GET /fleet/hosts``: declared
        membership + pool sizes, plus the roofline-derived SUGGESTED split
        for this host count (fleet/roles.py ``suggest_pool_split``) — what
        an operator compares their knobs against before re-rolling a host's
        ``--role``."""
        doc = self.roles.snapshot()
        total = len(self.registry.hosts())
        doc["suggested"] = (
            roles_mod.suggest_pool_split(total) if total else {}
        )
        return doc

    def _role_slo(self, objectives) -> dict:
        """Per-ROLE SLO verdicts: each role's verdicts judged over the
        merged scrapes of only that pool's hosts (generalist ``all`` hosts
        count toward every pool, exactly as placement sees them)."""
        out: dict[str, dict] = {}
        membership = self.roles.membership()
        hosts = self.registry.hosts()
        for role in roles_mod.ROLES:
            texts: dict[str, str] = {}
            for hid, info in hosts.items():
                if membership.get(hid, "all") not in (role, "all"):
                    continue
                text, _age = self.scoreboard.scrape_metrics(hid, info.base)
                if text is not None:
                    texts[hid] = text
            if texts:
                out[role] = slo.verdicts_from_text(
                    merge_metrics(texts), objectives
                )
        return out

    def fleet_metrics_view(self) -> tuple[str, dict]:
        """The fleet-wide merged Prometheus view (``GET /fleet/metrics``):
        every live backend's ``/metrics`` (scoreboard-cached, backoff-aware
        — a dead host serves its last scrape with a staleness marker, never
        a blocking fetch) plus this router's own registry, every series
        host-labeled. Returns ``(merged_text, stale_by_host)`` — stale
        means the host's section was never scraped or the host is failing
        (a backoff-served cache); a healthy host served from the freshness
        window is NOT stale (its cache is younger than the poll
        interval). The predicate is computed ONCE here — the
        ``pa_fleet_scrape_stale`` markers and ``/fleet/slo``'s
        ``scrape_stale`` field are the same judgment at the same
        instant."""
        self.publish_gauges()
        texts: dict[str, str] = {}
        ages: dict[str, float | None] = {}
        stale: dict[str, bool] = {}
        for hid, info in self.registry.hosts().items():
            text, age = self.scoreboard.scrape_metrics(hid, info.base)
            ages[hid] = age
            stale[hid] = (age is None
                          or self.scoreboard.in_backoff(hid)
                          or self.scoreboard.dead(hid))
            if text is not None:
                texts[hid] = text
        texts[self.router_id] = registry.render()
        merged = merge_metrics(texts)
        # Staleness markers: the merged view degrades, visibly, instead of
        # stalling behind a dead backend.
        extra = [
            "# TYPE pa_fleet_scrape_stale gauge",
        ]
        for hid in sorted(stale):
            extra.append(
                f'pa_fleet_scrape_stale{{host="{hid}"}} '
                f"{1.0 if stale[hid] else 0.0:.9g}"
            )
        extra.append("# TYPE pa_fleet_scrape_age_seconds gauge")
        for hid, age in sorted(ages.items()):
            if age is not None:
                extra.append(
                    f'pa_fleet_scrape_age_seconds{{host="{hid}"}} '
                    f"{age:.9g}"
                )
        return merged + "\n".join(extra) + "\n", stale

    def fleet_history_view(self, window_s: float | None = None) -> dict:
        """The fleet-wide metric history (``GET /fleet/history``): every
        backend's ``/metrics/history`` window merged host-labeled, riding
        the scoreboard's scrape cadence with the same staleness discipline
        as :meth:`fleet_metrics_view` — a dead or failing host serves its
        cached window marked ``stale``, never a blocking fetch. The
        router's own ring rides along under ``router_id`` when non-empty
        (routers sample too — heartbeat staleness is watched here)."""
        from ..utils import timeseries
        hosts: dict[str, dict] = {}
        for hid, info in self.registry.hosts().items():
            doc, age = self.scoreboard.scrape_history(hid, info.base,
                                                      window_s=window_s)
            hosts[hid] = {
                "window": doc,
                "age_s": age,
                "stale": (age is None
                          or self.scoreboard.in_backoff(hid)
                          or self.scoreboard.dead(hid)),
            }
        out = {
            "schema": "pa-fleet-history/v1",
            "router_id": self.router_id,
            "enabled": timeseries.enabled(),
            "hosts": hosts,
        }
        own = timeseries.ring.window(window_s=window_s)
        if (own.get("stats") or {}).get("points", 0):
            out["router"] = own
        return out

    def fleet_slo_view(self) -> dict:
        """Objective verdicts over the merged fleet view (``GET
        /fleet/slo``): the declared objectives (PA_SLO_OBJECTIVES or the
        defaults) judged against the merged ``pa_slo_request_seconds``
        histograms — fleet-wide and per host. Exposition histograms are
        lifetime-cumulative; the windowed view rides each host's own
        ``pa_slo_burn_rate`` gauges inside the merged text."""
        merged, stale = self.fleet_metrics_view()
        objectives = slo.objectives_from_env()
        hosts = {}
        for hid in self.registry.hosts():
            per = slo.verdicts_from_text(merged, objectives,
                                         labels={"host": hid})
            hosts[hid] = {
                "objectives": per,
                "scrape_stale": stale.get(hid, True),
            }
        doc = {
            "schema": "pa-fleet-slo/v1",
            "router_id": self.router_id,
            "enabled": slo.enabled(),
            "objectives": slo.verdicts_from_text(merged, objectives),
            "hosts": hosts,
        }
        if self.roles.disaggregated():
            # Per-role verdicts only when pools actually exist: a
            # single-pool fleet's /fleet/slo document stays byte-identical.
            doc["roles"] = self._role_slo(objectives)
        return doc

    def publish_gauges(self) -> None:
        self.scoreboard.publish_gauges()
        self.roles.publish_gauges()
        stats = self.stats()
        registry.gauge("pa_fleet_inflight",
                       stats["prompts"].get("inflight", 0),
                       help="prompts dispatched, entry not yet collected")
        registry.gauge("pa_fleet_queued",
                       stats["prompts"].get("queued", 0),
                       help="prompts awaiting a healthy backend")

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._history_sampler is not None:
            self._history_sampler.stop()
        if self.journal is not None:
            self.journal.close()


class _RouterHandler(BaseHTTPRequestHandler):
    router: FleetRouter  # injected by make_router
    protocol_version = "HTTP/1.1"
    # Header write + body write per response: without TCP_NODELAY the body
    # can stall behind a delayed ACK (see server.py's handler) — the front
    # door sits on every prompt's path, so it must not add Nagle stalls.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def do_GET(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        r = self.router
        if parts and parts[0] == "history":
            return self._send(
                200, r.history(parts[1] if len(parts) == 2 else None)
            )
        if url.path == "/journal":
            # Raw journal bytes from ``offset`` — the HTTP tail a standby's
            # JournalFollower drains (fleet/journal.py). 404 when this
            # router keeps no journal.
            if r.journal is None:
                return self._send(404, {"error": "router runs no journal"})
            qs = parse_qs(url.query)
            try:
                offset = int(qs.get("offset", ["0"])[0])
            except ValueError:
                return self._send(400, {"error": "offset must be an int"})
            try:
                with open(r.journal.path, "rb") as f:
                    f.seek(max(0, offset))
                    chunk = f.read()
            except OSError:
                chunk = b""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(chunk)))
            self.end_headers()
            return self.wfile.write(chunk)
        if url.path == "/health":
            doc = {
                "schema": FLEET_HEALTH_SCHEMA,
                "router_id": r.router_id,
                "role": "active" if r.active else "standby",
                "journal": r.journal.path if r.journal is not None else None,
                "hosts": r.scoreboard.snapshot(),
                "ring": r.registry.snapshot(),
                **r.stats(),
            }
            if tracing.on():
                doc["fleet_hop_p95_ms"] = tracing.fleet_hop_p95_ms(
                    tracing.export()
                )
            return self._send(200, doc)
        if url.path == "/metrics":
            r.publish_gauges()
            body = registry.render().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            return self.wfile.write(body)
        if url.path == "/fleet/hosts":
            return self._send(200, {
                "ring": r.registry.snapshot(),
                "scoreboard": r.scoreboard.snapshot(),
                "roles": r.roles_view(),
            })
        if url.path == "/fleet/metrics":
            # ONE Prometheus view of the whole fleet: every backend's
            # /metrics merged host-labeled with the router's own, dead
            # hosts degrading to their cached scrape + a staleness marker.
            merged, _ = r.fleet_metrics_view()
            body = merged.encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            return self.wfile.write(body)
        if url.path == "/fleet/history":
            # Merged per-host metric history: each backend's cached
            # /metrics/history window, dead hosts degrading to their last
            # scrape with a staleness marker (scripts/console.py consumes
            # this).
            qs = parse_qs(url.query)
            window = None
            if qs.get("window"):
                try:
                    window = float(qs["window"][0])
                except ValueError:
                    return self._send(
                        400, {"error": "window must be seconds"})
            return self._send(200, r.fleet_history_view(window_s=window))
        if url.path == "/fleet/slo":
            return self._send(200, r.fleet_slo_view())
        if url.path == "/fleet/trace":
            # The stitched cross-host timeline for one prompt (the request-
            # forensics collector; scripts/explain.py consumes this).
            qs = parse_qs(url.query)
            pid = (qs.get("prompt_id") or [None])[0]
            if not pid:
                return self._send(400, {"error": "prompt_id required"})
            doc = r.stitch_trace(pid)
            return self._send(404 if doc.get("error") else 200, doc)
        return self._send(404, {"error": f"no route {url.path}"})

    def do_POST(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        r = self.router
        try:
            payload = self._body()
        except (ValueError, json.JSONDecodeError) as e:
            return self._send(400, {"error": f"bad JSON: {e}"})
        if url.path == "/prompt":
            graph = payload.get("prompt")
            if not isinstance(graph, dict) or not graph:
                return self._send(
                    400,
                    {"error": 'body must carry a non-empty {"prompt": {...}}'}
                )
            try:
                pid, number = r.submit(graph, payload.get("extra_data"))
            except StandbyRouter as e:
                return self._send(503, {"error": str(e), "role": "standby"})
            except FleetSaturated as e:
                return self._send(429, {"error": str(e)})
            except NoHealthyHost as e:
                return self._send(503, {"error": str(e)})
            except BackendRejected as e:
                # The backend's own client-error verdict, passed through.
                return self._send(e.code, {"error": str(e)})
            return self._send(200, {"prompt_id": pid, "number": number})
        if url.path == "/history/phase":
            # Phase boundary stamp (loadgen rung edges): mark the router's
            # own ring, then fan out best-effort to every live backend so
            # each host's history window carries the same phase labels —
            # a dead host just misses the mark, it never blocks the stamp.
            label = payload.get("label")
            if not label:
                return self._send(400, {"error": "label required"})
            state = payload.get("state", "begin")
            detail = payload.get("detail")
            from ..utils import timeseries
            timeseries.ring.mark_phase(str(label), state=str(state),
                                       detail=detail)
            body = json.dumps({"label": str(label), "state": str(state),
                               "detail": detail}).encode()
            stamped = [r.router_id]
            for hid, info in r.registry.hosts().items():
                if r.scoreboard.dead(hid):
                    continue
                try:
                    req = urllib.request.Request(
                        info.base.rstrip("/") + "/history/phase",
                        data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=2.0):
                        pass
                    stamped.append(hid)
                except (urllib.error.URLError, OSError, ValueError):
                    continue
            return self._send(200, {"ok": True, "stamped": stamped})
        if url.path == "/fleet/register":
            host_id = payload.get("host_id")
            base = payload.get("base")
            if not host_id or not base:
                return self._send(400, {"error": "host_id and base required"})
            try:
                role = roles_mod.normalize_role(payload.get("role"))
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            joined = r.registry.heartbeat(str(host_id), str(base), role=role)
            if joined:
                # Poll immediately so the joiner is placeable without
                # waiting out a scoreboard interval — and open the
                # prefer-warm dwell: keys the reshuffle re-homed onto this
                # cold joiner keep going to warm siblings until it warms.
                r.scoreboard.poll_host(str(host_id), str(base).rstrip("/"))
                r.note_ring_change()
            return self._send(200, {"joined": joined})
        if url.path == "/fleet/leave":
            host_id = str(payload.get("host_id") or "")
            return self._send(200, {"removed": r.leave(host_id)})
        if url.path == "/fleet/drain":
            host_id = str(payload.get("host_id") or "")
            try:
                resp = r.drain(host_id)
            except KeyError as e:
                return self._send(404, {"error": str(e)})
            except (OSError, urllib.error.HTTPError) as e:
                return self._send(502, {"error": f"drain proxy failed: {e}"})
            return self._send(200, resp)
        if url.path == "/interrupt":
            return self._send(200, {"dropped": r.interrupt()})
        return self._send(404, {"error": f"no route {url.path}"})


def make_router(
    host: str = "127.0.0.1", port: int = 8187,
    backends=None, **router_kwargs,
) -> tuple[ThreadingHTTPServer, FleetRouter]:
    """Build (but don't start) the router HTTP server. ``backends`` seeds
    static ring members: ``(host_id, base)`` tuples or bare base URLs (the
    host_id then derives from the URL). Port 0 picks an ephemeral port."""
    router = FleetRouter(**router_kwargs)
    for b in backends or ():
        if isinstance(b, (tuple, list)):
            hid, base = b
        else:
            base = str(b)
            hid = urlparse(base).netloc or base
        router.registry.add_static(str(hid), str(base))
    handler = type("Handler", (_RouterHandler,), {"router": router})

    class _RouterHTTPServer(ThreadingHTTPServer):
        # Default listen backlog (5) drops client poll bursts; the front
        # door must absorb every client's history polling.
        request_queue_size = 128

    srv = _RouterHTTPServer((host, port), handler)
    return srv, router


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8187)
    ap.add_argument("--backends", default="",
                    help="comma list of backend base URLs (static ring "
                         "seeds; elastic hosts join via /fleet/register)")
    ap.add_argument("--depth", type=int, default=4,
                    help="per-host admission depth before spilling")
    ap.add_argument("--poll-s", type=float, default=1.0,
                    help="health-poll interval per host")
    ap.add_argument("--ttl-s", type=float, default=10.0,
                    help="heartbeat TTL before an elastic host expires")
    ap.add_argument("--max-attempts", type=int, default=4)
    ap.add_argument("--trace", action="store_true",
                    help="enable span tracing (fleet-prompt / fleet-hop)")
    ap.add_argument("--journal", default=None,
                    help="durable prompt-journal path (pa-fleet-journal/v1 "
                         "JSONL + <path>.lease): submissions survive this "
                         "process — a standby tailing the same path (or "
                         "--follow) replays them after a crash")
    ap.add_argument("--standby", action="store_true",
                    help="start as a standby: tail --journal, serve "
                         "/history from its shadows, refuse /prompt (503), "
                         "and take over when the primary's lease goes stale")
    ap.add_argument("--follow", default=None,
                    help="primary router base URL: tail its journal over "
                         "HTTP (GET /journal) into --journal instead of "
                         "reading a shared path (implies --standby)")
    ap.add_argument("--lease-ttl-s", type=float, default=10.0,
                    help="lease staleness a standby treats as primary death "
                         "(keep it ABOVE the scoreboard poll timeout: a "
                         "sweep stalled on one slow backend must not read "
                         "as router death)")
    args = ap.parse_args()
    if args.trace:
        tracing.enable()
    if args.follow and not args.journal:
        ap.error("--follow requires --journal (the local tail copy)")
    if args.standby and not args.journal:
        ap.error("--standby requires --journal (what to replay)")
    srv, router = make_router(
        args.host, args.port,
        backends=[b for b in args.backends.split(",") if b],
        fleet_registry=FleetRegistry(ttl_s=args.ttl_s),
        scoreboard=Scoreboard(poll_s=args.poll_s),
        saturation_depth=args.depth, max_attempts=args.max_attempts,
        journal=PromptJournal(args.journal) if args.journal else None,
        standby=bool(args.standby or args.follow),
        lease_ttl_s=args.lease_ttl_s,
        follower=(JournalFollower(args.follow, args.journal)
                  if args.follow else None),
    )
    role = "standby" if not router.active else "router"
    # palint: allow[observability] router startup banner (CLI surface)
    print(f"ParallelAnything fleet {role} on http://{args.host}:{args.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.shutdown()


if __name__ == "__main__":
    main()
