"""Fleet membership: the consistent-hash ring and the heartbeat registry.

The reference is single-process — one host owns the prompt queue and every
loaded model (any_device_parallel.py's module-level parallel-model cache).
A fleet needs two things that queue never had:

- **membership**: which backend hosts exist right now. Hosts join by POSTing
  registration heartbeats to the router (``HeartbeatClient`` below is the
  backend-side thread ``server.py --fleet-router`` starts); a host whose
  heartbeats stop falls out after ``ttl_s`` (elastic leave — crash or
  scale-down look identical). Statically configured hosts (the router's
  ``--backends`` flag) never expire by heartbeat: their liveness is the
  scoreboard's health polling (fleet/scoreboard.py).
- **placement order**: a consistent-hash ring over the live hosts
  (``vnodes`` virtual nodes per host smooth the key distribution). Keys are
  MODEL identities, not prompt ids: every prompt for one model hashes to the
  same primary host, so that host's compiled step programs and pinned
  weights stay warm (the MPMD keep-programs-resident result, PAPERS.md
  arxiv 2412.14374) — and ring membership changes only move the keys
  adjacent to the joined/left host, not the whole map.

Vnode counts are CAPACITY-WEIGHTED (ROADMAP fleet-hardening item 2): a
host's share of the ring scales with its banked speed — per-host step-time
history from the perf ledger (``utils/roofline.host_step_weights``: loadgen
per-host ``server_step_p50_s`` — the fleet's own same-workload
measurements; never bench s/it, which is rung-dependent), normalized to
mean 1.0 — so a v5e-8 takes proportionally more keys than a v5e-4.
Hosts with no history weigh 1.0 (the pre-calibration equal split); the
router refreshes weights from the ledger at registry construction. This is
the first cross-host consumer of the roofline calibration discipline: ring
share follows measured speed, not the reference's static free-VRAM scoring
(any_device_parallel.py:724-766).

Pure host-side bookkeeping: nothing here imports jax
(``utils/roofline``'s module level is stdlib-only by contract).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
import urllib.request

from ..utils import retry
from ..utils.logging import get_logger

log = get_logger()


def stable_hash(key: str) -> int:
    """Process-independent 64-bit hash (``hash()`` is salted per process —
    a ring that moves on every restart would defeat warm affinity)."""
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big", signed=False
    )


def ledger_capacity_weights(ledger_path: str | None = None) -> dict[str, float]:
    """Per-host ring weights from the perf ledger's banked step times
    (``utils/roofline.host_step_weights``); ``{}`` — equal weights — when
    there is no history or the ledger is unreadable. Best-effort by
    contract: a corrupt ledger must never keep a router from starting."""
    try:
        from ..utils import roofline

        return roofline.host_step_weights(
            roofline.ledger_records(ledger_path)
        )
    except Exception:
        return {}


class HashRing:
    """Consistent-hash ring: ``sequence(key)`` is the deterministic host
    preference order for a key — the primary first, then each successive
    distinct host clockwise (the spill/failover order)."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._ring: list[tuple[int, str]] = []  # sorted (point, host_id)

    def rebuild(self, host_ids, weights: dict[str, float] | None = None) -> None:
        """``weights`` scales each host's vnode count (capacity weighting:
        2.0 → twice the ring share; min 1 vnode so a slow host still owns
        keys). Unlisted hosts weigh 1.0 — equal split, the no-history
        fallback. Vnode hash points depend only on (host, index), so a
        weight change only adds/removes that host's highest-index vnodes —
        membership churn stays local, the consistent-hash property."""
        ring = []
        for hid in host_ids:
            n = max(1, round(self.vnodes * float((weights or {}).get(hid, 1.0))))
            for v in range(n):
                ring.append((stable_hash(f"{hid}#{v}"), hid))
        ring.sort()
        self._ring = ring

    def sequence(self, key: str) -> list[str]:
        """Distinct hosts in ring order starting at the key's point."""
        if not self._ring:
            return []
        point = stable_hash(key)
        points = [p for p, _ in self._ring]
        # First vnode clockwise of the key's point (wrapping).
        import bisect

        start = bisect.bisect_left(points, point) % len(self._ring)
        seen: list[str] = []
        for i in range(len(self._ring)):
            hid = self._ring[(start + i) % len(self._ring)][1]
            if hid not in seen:
                seen.append(hid)
        return seen


@dataclasses.dataclass
class HostInfo:
    host_id: str
    base: str                     # http://host:port the router reaches it at
    static: bool = False          # configured, not heartbeat-registered
    last_beat: float = 0.0        # time.monotonic() of the last heartbeat
    joined_monotonic: float = 0.0
    role: str = "all"             # role pool (fleet/roles.py); "all" = every pool


class FleetRegistry:
    """Live membership + the ring built over it. Thread-safe: the router's
    HTTP threads call ``heartbeat``/``remove`` while the monitor thread reads
    ``hosts``/``sequence``."""

    def __init__(self, ttl_s: float = 10.0, vnodes: int = 64,
                 capacity_weights: dict[str, float] | None = None,
                 capacity_from_ledger: bool = True):
        self.ttl_s = float(ttl_s)
        self._hosts: dict[str, HostInfo] = {}  # guarded-by: _lock
        self._ring = HashRing(vnodes=vnodes)
        self._lock = threading.Lock()
        self._weights: dict[str, float] = dict(capacity_weights or {})  # guarded-by: _lock
        if capacity_from_ledger and not self._weights:
            self._weights = ledger_capacity_weights()

    def _rebuild(self) -> None:
        self._ring.rebuild(sorted(self._hosts), self._weights)

    def set_capacity_weights(self, weights: dict[str, float]) -> None:
        """Replace the ring's capacity weights and rebuild — the operator /
        refresh hook (e.g. after a loadgen run banks fresh per-host step
        times). Ring changes stay local to the hosts whose weight moved."""
        with self._lock:
            self._weights = dict(weights or {})
            self._rebuild()

    def capacity_weights(self) -> dict[str, float]:
        with self._lock:
            return dict(self._weights)

    def add_static(self, host_id: str, base: str, role: str = "all") -> None:
        """Configured backend (router ``--backends``): in the ring until
        explicitly removed — liveness is the scoreboard's problem."""
        with self._lock:
            self._hosts[host_id] = HostInfo(
                host_id, base.rstrip("/"), static=True,
                last_beat=time.monotonic(),
                joined_monotonic=time.monotonic(),
                role=role or "all",
            )
            self._rebuild()

    def heartbeat(self, host_id: str, base: str, role: str = "all") -> bool:
        """One registration heartbeat. Returns True when this JOINED a new
        host (ring changed), False for a refresh. ``role`` is the host's
        declared pool (fleet/roles.py) and follows the beat — a restart
        under a new ``--role`` re-pools the host without a leave/join."""
        now = time.monotonic()
        with self._lock:
            info = self._hosts.get(host_id)
            if info is None:
                self._hosts[host_id] = HostInfo(
                    host_id, base.rstrip("/"), last_beat=now,
                    joined_monotonic=now, role=role or "all",
                )
                self._rebuild()
                log.info("fleet host joined: %s (%s)", host_id, base)
                return True
            info.last_beat = now
            info.base = base.rstrip("/")
            info.role = role or "all"
            return False

    def remove(self, host_id: str) -> bool:
        with self._lock:
            if self._hosts.pop(host_id, None) is None:
                return False
            self._rebuild()
        log.info("fleet host left: %s", host_id)
        return True

    def expire(self) -> list[str]:
        """Drop heartbeat-registered hosts whose beats stopped; returns the
        expired host ids (the router fails their in-flight prompts over)."""
        now = time.monotonic()
        dropped = []
        with self._lock:
            for hid, info in list(self._hosts.items()):
                if not info.static and now - info.last_beat > self.ttl_s:
                    del self._hosts[hid]
                    dropped.append(hid)
            if dropped:
                self._rebuild()
        for hid in dropped:
            log.warning("fleet host expired (no heartbeat): %s", hid)
        return dropped

    def hosts(self) -> dict[str, HostInfo]:
        with self._lock:
            return dict(self._hosts)

    def base_of(self, host_id: str) -> str | None:
        with self._lock:
            info = self._hosts.get(host_id)
            return info.base if info else None

    def sequence(self, key: str) -> list[str]:
        """Host preference order for a model key (primary first)."""
        with self._lock:
            return self._ring.sequence(key)

    def snapshot(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "host_id": i.host_id, "base": i.base, "static": i.static,
                    "heartbeat_age_s": round(now - i.last_beat, 3),
                    "role": i.role,
                }
                for i in self._hosts.values()
            ]


class HeartbeatClient:
    """Backend-side registration heartbeats (``server.py --fleet-router``):
    POST ``{host_id, base}`` to the router's ``/fleet/register`` every
    ``interval_s`` so the host joins the ring elastically and falls out when
    it dies. Best-effort by design: a down router must never take the
    backend with it.

    Round 14: reconnects ride the shared retry policy (utils/retry.py) — an
    unreachable router used to be re-beat at the fixed cadence forever (a
    hot loop of socket timeouts when the interval is short); now consecutive
    failures back off exponentially (deterministic jitter, capped) and the
    first success snaps back to the normal cadence. ``on_rejoin`` fires when
    the router reports this beat JOINED the ring anew (we had fallen off —
    router restart, standby takeover, our beats lost): the server wires it
    to ``resume_if_auto_drained()`` so a returning host re-opens admission
    instead of rejoining dark — but NEVER overrides an operator-initiated
    drain (a router restart mid-maintenance must not resurrect the host). A
    live host's refresh beats (``joined=False``) never fire it."""

    def __init__(self, router_base: str, host_id: str, base: str,
                 interval_s: float = 2.0, on_rejoin=None,
                 retry_policy: "retry.RetryPolicy | None" = None,
                 role: str = "all"):
        self.router_base = router_base.rstrip("/")
        self.host_id = host_id
        self.base = base
        self.role = role or "all"
        self.interval_s = float(interval_s)
        self.on_rejoin = on_rejoin
        self.retry_policy = retry_policy or dataclasses.replace(
            retry.HEARTBEAT, base_s=max(0.5, self.interval_s)
        )
        self._failures = 0
        self._ever_joined = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat_once(self, timeout: float = 5.0) -> bool:
        # Fault site (utils/faults.py): a lost heartbeat is silently
        # swallowed — the router sees this host go dark exactly as if the
        # network ate the POST (TTL expiry → failover), while the host
        # itself stays healthy. The chaos rehearsal for asymmetric partitions.
        from ..utils import faults

        if faults.check("heartbeat-loss", key=self.host_id) is not None:
            self._failures += 1
            return False
        # Fault site (utils/faults.py): the backend→router half of a
        # NETWORK PARTITION — unlike heartbeat-loss (this host's beats
        # alone go dark), the chaos matrix fires this together with the
        # router→backend half on the same host, so BOTH directions are cut
        # at once: the router fails our in-flight prompts over while we
        # keep executing into a void. Keyed "{host_id}->router".
        if faults.check("network-partition", key=f"{self.host_id}->router") is not None:
            self._failures += 1
            return False
        req = urllib.request.Request(
            self.router_base + "/fleet/register",
            data=json.dumps(
                {"host_id": self.host_id, "base": self.base,
                 "role": self.role}
            ).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                resp = json.loads(r.read() or b"{}")
        except (OSError, ValueError):
            self._failures += 1
            return False
        rejoined = bool(resp.get("joined")) and self._ever_joined
        self._ever_joined = True
        self._failures = 0
        if rejoined and self.on_rejoin is not None:
            try:
                self.on_rejoin()
            except Exception:  # noqa: BLE001 — a rejoin hook must not kill beats
                pass
        return True

    def next_wait_s(self) -> float:
        """The loop's sleep before the next beat: the normal cadence while
        healthy, the policy's backoff window after consecutive failures."""
        if self._failures == 0:
            return self.interval_s
        return max(self.interval_s, self.retry_policy.backoff_s(
            self._failures - 1, key=self.host_id
        ))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._stop.wait(self.next_wait_s())

    def start(self) -> "HeartbeatClient":
        self._thread = threading.Thread(
            target=self._loop, name="pa-fleet-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
