"""TPU-native parallel-anything: device-chain parallelism for diffusion models on JAX/XLA.

A brand-new framework with the capabilities of ComfyUI-ParallelAnything
(reference: /root/reference/any_device_parallel.py): build a chain of devices with
per-device workload percentages, wrap a diffusion model once, and have every sampler
step execute in parallel across the chain. Where the reference replicates torch
modules across CUDA devices with threads + PCIe copies, this framework expresses the
same capabilities as sharded, jit-compiled SPMD programs over a `jax.sharding.Mesh`:

- data parallelism  = batch-axis `NamedSharding` (reference: threaded batch split,
  any_device_parallel.py:1317-1422)
- pipeline (batch=1) = contiguous block-range placement over mesh stages
  (reference: ParallelBlock wrapping, any_device_parallel.py:1152-1198)
- replication       = a single weight pytree + sharding specs (reference:
  safe_model_clone, any_device_parallel.py:586-722 — entirely absent here)
- communication     = XLA ICI collectives (reference: Tensor.to over PCIe)

Beyond parity, long-context sequence/context parallelism (ring attention, Ulysses
all-to-all) and multi-host meshes are first-class.
"""

from .version import __version__

from .devices.discovery import (
    available_devices,
    get_device,
    device_platform,
    default_device,
)
from .devices.memory import free_memory_bytes, total_memory_bytes

from .parallel.chain import DeviceLink, DeviceChain
from .parallel.split import (
    normalize_weights,
    largest_remainder_split,
    weighted_batch_split,
    blend_memory_weights,
    blend_speed_weights,
    block_ranges,
    batch_size_of,
    split_tree,
    split_kwargs,
    concat_results,
)
from .parallel.mesh import build_mesh, mesh_axis_names
from .parallel.orchestrator import parallelize, ParallelConfig, ParallelModel
from .parallel.sequence import sequence_parallel_attention
from .pipelines import (
    StableDiffusionPipeline,
    FluxPipeline,
    Sd3Pipeline,
    WanVideoPipeline,
)
from .models.generic import derive_pipeline_spec, wrap_flax_module
from .host import run_workflow, WorkflowCache, WorkflowError
from .utils.metrics import StepTimer, trace

__all__ = [
    "__version__",
    "available_devices",
    "get_device",
    "device_platform",
    "default_device",
    "free_memory_bytes",
    "total_memory_bytes",
    "DeviceLink",
    "DeviceChain",
    "normalize_weights",
    "largest_remainder_split",
    "weighted_batch_split",
    "blend_memory_weights",
    "blend_speed_weights",
    "block_ranges",
    "batch_size_of",
    "split_tree",
    "split_kwargs",
    "concat_results",
    "build_mesh",
    "mesh_axis_names",
    "parallelize",
    "ParallelConfig",
    "ParallelModel",
    "sequence_parallel_attention",
    "StableDiffusionPipeline",
    "FluxPipeline",
    "WanVideoPipeline",
    "Sd3Pipeline",
    "derive_pipeline_spec",
    "wrap_flax_module",
    "run_workflow",
    "WorkflowCache",
    "WorkflowError",
    "StepTimer",
    "trace",
]
