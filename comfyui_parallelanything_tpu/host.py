"""Standalone workflow-graph executor — the host layer the reference borrows.

The reference node pack runs inside ComfyUI, which supplies graph storage,
topological execution, and link resolution (SURVEY §1 L5: "external host").
This module makes the framework its own host: it executes ComfyUI API-format
workflow JSON directly against ``nodes.NODE_CLASS_MAPPINGS``, so a user of the
reference can bring their exported workflow file and run it here unchanged
(given the node names in this pack).

Format (the ComfyUI ``/prompt`` API shape):

    {
      "1": {"class_type": "ParallelDevice",
            "inputs": {"device_id": "tpu:0", "percentage": 50.0}},
      "2": {"class_type": "ParallelDevice",
            "inputs": {"device_id": "tpu:1", "percentage": 50.0,
                        "previous_devices": ["1", 0]}},
      ...
    }

A two-element list ``[node_id, output_index]`` is a link; everything else is a
literal widget value. ComfyUI's executor treats any link-shaped value as a link
regardless of the declared input type (exported workflows routinely wire
widget inputs, e.g. a seed from a seed-control node via convert-widget-to-
input), so declared primitive widgets (INT/FLOAT/STRING/BOOLEAN) also resolve
link-shaped values — but only when the referenced id names a node in the
graph, which keeps genuine list literals safe. Node classes follow the
declarative protocol (``INPUT_TYPES`` / ``RETURN_TYPES`` / ``FUNCTION``) — the
same protocol the reference registers into ComfyUI
(any_device_parallel.py:1473-1483).
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from typing import Any


from .utils import slo, tracing
from .utils.progress import Interrupted, check_interrupt


class WorkflowError(ValueError):
    """A malformed or unexecutable workflow graph (unknown node/class, bad
    link, cycle) — raised with the offending node id in the message."""


class WorkflowCache:
    """Cross-run output cache with ComfyUI-style invalidation.

    A plain ``outputs`` dict reuses entries unconditionally; this cache instead
    keys each node's outputs to a signature of (class_type, literal inputs,
    upstream signatures), so editing a node — or anything upstream of it —
    re-executes exactly the stale subgraph. When a stale or dropped entry is
    evicted, any output value exposing ``cleanup()`` (a ParallelModel) is torn
    down: the host-side analogue of the reference's ``weakref.finalize``
    teardown firing when ComfyUI replaces a MODEL output
    (any_device_parallel.py:1459, 211-282) — without it, the cache would hold
    every superseded model's device placements alive indefinitely.

    Concurrency (round 7, the multi-worker server): all mutation happens
    under ``self.lock``, and executions run against a SNAPSHOT of the results
    dict merged back via ``merge()`` when the run finishes — two prompts
    executing at once can never observe each other's half-built state. The
    remaining multi-tenant caveat is inherited from the ComfyUI cache design
    itself: a prompt that invalidates a node (different checkpoint into the
    same node id) tears down the incumbent even if a concurrent prompt's
    snapshot still uses it — serving workloads share models by construction,
    which is also what makes continuous batching worth having.
    """

    def __init__(self) -> None:
        self.results: dict[str, tuple] = {}     # guarded-by: lock
        self.signatures: dict[str, str] = {}    # guarded-by: lock
        self.lock = threading.RLock()

    def evict(self, nid: str) -> None:
        """Drop one node's cached outputs, tearing down teardownable values
        (unless a still-cached node shares the same object — the standard
        ComfyUI MODEL pass-through)."""
        self.evict_stale({nid})

    @staticmethod
    def _teardown(value) -> None:
        cleanup = getattr(value, "cleanup", None)
        if callable(cleanup):
            try:
                cleanup()
            except Exception:
                pass
        # Cross-request embed cache (models/embed_cache.py): an evicted CLIP
        # wire releases its cached encoder outputs eagerly — the same
        # eager-teardown discipline this cache applies to models, extended
        # to the content-addressed layer underneath it. Identity-safe: the
        # keep_ids check above this call site already proved no surviving
        # entry shares the wire, and owner tokens are lifetime-unique.
        try:
            from .models.embed_cache import release_wire

            release_wire(value)
        except Exception:
            pass

    def evict_stale(self, stale) -> None:
        """Drop every cached entry in ``stale``. A value is torn down only when
        NO surviving entry still holds the same object: pass-through nodes
        (e.g. a sampler returning the MODEL it received) share identity with
        their upstream, and tearing down via the stale downstream entry would
        gut the still-valid upstream cache."""
        with self.lock:
            stale = set(stale)
            keep_ids = {
                id(v)
                for nid, out in self.results.items()
                if nid not in stale
                for v in out
            }
            torn: set[int] = set()
            for nid in stale:
                out = self.results.pop(nid, None)
                self.signatures.pop(nid, None)
                for value in out or ():
                    if id(value) in keep_ids or id(value) in torn:
                        continue
                    torn.add(id(value))
                    self._teardown(value)

    def snapshot(self, sigs: dict[str, str]) -> dict[str, tuple]:
        """Evict entries stale against this run's signatures and return a
        consistent copy of the survivors for the run to execute against (one
        lock hold — no other run's merge can interleave)."""
        with self.lock:
            self.evict_stale(
                nid for nid in self.results
                if nid not in sigs or self.signatures.get(nid) != sigs[nid]
            )
            return dict(self.results)

    def merge(self, results: dict[str, tuple], sigs: dict[str, str]) -> None:
        """Bank one run's (possibly partial — interrupts keep what completed)
        outputs. A node another run already banked with the same signature
        keeps the incumbent; our duplicate (a cold-start race computed the
        same thing twice) is NOT torn down here — the caller's returned
        ``results`` still references it, so destroying it would hand the
        caller dead device buffers. It simply never enters the cache and is
        reclaimed when the caller drops it (ParallelModel carries a GC
        finalizer honoring the purge flags). A different-signature incumbent
        is evicted with full teardown discipline before ours lands."""
        with self.lock:
            for nid, out in results.items():
                prev = self.results.get(nid)
                if prev is not None and self.signatures.get(nid) == sigs.get(nid):
                    continue  # incumbent wins; our duplicate stays caller-owned
                if prev is not None:
                    self.evict_stale({nid})
                self.results[nid] = out
                self.signatures[nid] = sigs[nid]


def _is_link(v: Any) -> bool:
    return (
        isinstance(v, list)
        and len(v) == 2
        and isinstance(v[0], (str, int))
        and isinstance(v[1], int)
    )


_WIDGET_PRIMITIVES = {"INT", "FLOAT", "STRING", "BOOLEAN"}


def _wire_inputs(cls: type) -> tuple[set[str], set[str], dict[str, str]]:
    """(wire_input_names, declared_input_names, hidden_inputs) from a node's
    INPUT_TYPES, in ONE call (INPUT_TYPES may scan the filesystem for dropdown
    options — it must not be re-derived per execution).

    Disambiguates link-vs-literal for two-element list values: a declared widget
    (primitive type or dropdown options) takes literals; a declared wire type
    (e.g. "MODEL") takes links. Undeclared names fall back to the link shape
    heuristic. ``hidden`` entries (ComfyUI executor semantics) are values the
    HOST injects — PROMPT (the workflow dict), UNIQUE_ID (the node id)."""
    wires: set[str] = set()
    declared: set[str] = set()
    hidden: dict[str, str] = {}
    try:
        spec = cls.INPUT_TYPES()
    except Exception:
        return wires, declared, hidden
    for key, group in spec.items():
        if not isinstance(group, dict):
            continue
        if key == "hidden":
            hidden = {k: v for k, v in group.items() if isinstance(v, str)}
            continue
        for name, decl in group.items():
            declared.add(name)
            typ = decl[0] if isinstance(decl, (tuple, list)) and decl else decl
            if isinstance(typ, str) and typ not in _WIDGET_PRIMITIVES:
                wires.add(name)
    return wires, declared, hidden


STAGES = ("encode", "denoise", "decode")


def _intrinsic_stage(class_type) -> int | None:
    """Stage rank of a node class, or None for neutral nodes. The SAME
    class_type substring vocabulary as the SLO stage decomposition in
    ``exec_visit`` below ("Decode" / "Sampler" / "TextEncode", checked in
    that order) — one vocabulary, two consumers, so a node's stage rank and
    its stage histogram always agree."""
    ct = str(class_type or "")
    if "Decode" in ct:
        return 2
    if "Sampler" in ct:
        return 1
    if "TextEncode" in ct:
        return 0
    return None


def carve_stages(workflow) -> dict | None:
    """Carve a workflow graph into encode / denoise / decode sub-plans for
    role-pool dispatch (fleet/roles.py) — the stage-level MPMD placement the
    reference's whole-sampler-per-thread design has no room for
    (any_device_parallel.py:817-905).

    Class-AGNOSTIC (the router has no node registry): links are detected by
    shape plus the referenced id naming a graph node; ranks come from
    class_type substrings (:func:`_intrinsic_stage`). Neutral nodes inherit
    the max rank among their ancestors (a LatentUpscale after the sampler is
    denoise work; a SaveImage after decode is decode work); nodes with no
    ranked ancestor are FREE (loaders) and replicate into every stage's
    closure. Each stage's executable ``graph`` is the full upstream closure
    of its members, so a host holding no hand-off handles simply recomputes
    the prefix locally — bitwise by the fold_in contract, never an error.

    Returns ``None`` whenever the graph doesn't cleanly split — fewer than
    two intrinsic stages present, a cycle, a malformed spec, or a
    non-monotone stage order (highres-fix: a Decode feeding a second
    Sampler) — and callers fall back to the single-dispatch path, which
    keeps ``--role all`` fleets bitwise-unchanged. Otherwise::

        {"stages": [{"stage": name, "nodes": [member ids],
                     "graph": {closure subgraph}, "needs": [handle ids],
                     "exports": [handle ids]}, ...]}

    ``needs`` are the earlier-stage node ids whose output handles this
    stage wants preseeded; ``exports`` are this stage's node ids some later
    stage needs — the boundary values a backend banks content-addressed
    (roles.StageStore) and the journal's stage lineage records.
    """
    if not isinstance(workflow, dict):
        return None
    graph = {str(k): v for k, v in workflow.items()}
    deps: dict[str, list[str]] = {}
    for nid, spec in graph.items():
        if not isinstance(spec, dict):
            return None
        ds: list[str] = []
        for v in (spec.get("inputs") or {}).values():
            if _is_link(v) and str(v[0]) in graph:
                dep = str(v[0])
                if dep not in ds:
                    ds.append(dep)
        deps[nid] = ds

    # Kahn topological order; leftovers mean a cycle → no carve.
    indeg = {nid: 0 for nid in graph}
    rdeps: dict[str, list[str]] = {nid: [] for nid in graph}
    for nid, ds in deps.items():
        indeg[nid] = len(ds)
        for d in ds:
            rdeps[d].append(nid)
    ready = sorted(nid for nid, n in indeg.items() if n == 0)
    topo: list[str] = []
    while ready:
        nid = ready.pop(0)
        topo.append(nid)
        for child in rdeps[nid]:
            indeg[child] -= 1
            if indeg[child] == 0:
                ready.append(child)
    if len(topo) != len(graph):
        return None

    rank: dict[str, int | None] = {}
    intrinsic_present: set[int] = set()
    for nid in topo:
        anc = max(
            (rank[d] for d in deps[nid] if rank.get(d) is not None),
            default=None,
        )
        r = _intrinsic_stage(graph[nid].get("class_type"))
        if r is None:
            rank[nid] = anc
        else:
            intrinsic_present.add(r)
            if anc is not None and anc > r:
                return None  # stage order not monotone along this edge
            rank[nid] = r
    if len(intrinsic_present) < 2:
        return None

    present = sorted({r for r in rank.values() if r is not None})
    stages = []
    for s in present:
        members = [nid for nid in topo if rank[nid] == s]
        # Full upstream closure: members plus every transitive dependency.
        closure: dict[str, Any] = {}
        stack = list(members)
        while stack:
            nid = stack.pop()
            if nid in closure:
                continue
            closure[nid] = graph[nid]
            stack.extend(deps[nid])
        needs = sorted({
            d for m in members for d in deps[m]
            if rank.get(d) is not None and rank[d] < s
        })
        stages.append({
            "stage": STAGES[s], "nodes": members,
            "graph": closure, "needs": needs, "exports": [],
        })
    by_rank = {st["stage"]: st for st in stages}
    for st in stages:
        for d in st["needs"]:
            owner = by_rank[STAGES[rank[d]]]
            if d not in owner["exports"]:
                owner["exports"].append(d)
    for st in stages:
        st["exports"].sort()
    return {"stages": stages}


def run_workflow(
    workflow: Any,
    class_mappings: dict[str, type] | None = None,
    outputs: "dict[str, tuple] | WorkflowCache | None" = None,
    on_node=None,
    on_cached=None,
    preseed: dict[str, tuple] | None = None,
) -> dict[str, tuple]:
    """Execute a ComfyUI API-format workflow; returns ``{node_id: outputs}``.

    ``workflow`` is the dict itself or a path to a JSON file. ``class_mappings``
    extends/overrides ``nodes.NODE_CLASS_MAPPINGS`` (e.g. to register custom
    nodes like the hosts the reference targets allow). ``outputs`` pre-seeds
    node results: a plain dict reuses entries unconditionally (re-running a
    graph only executes nodes not already present); a ``WorkflowCache`` adds
    ComfyUI-style invalidation — stale/dropped entries are evicted (tearing
    down teardownable values like parallel models) and only the changed
    subgraph re-executes. Cache mode requires an acyclic graph.

    ``on_node(nid)`` fires immediately before each node actually executes
    (cached nodes are skipped, matching ComfyUI's ``executing`` event, which
    the server layer forwards to /ws clients). ``on_cached(nids)`` fires once
    before execution with the sorted graph nodes served from pre-seeded
    outputs/cache (ComfyUI's ``execution_cached`` event). A
    ``utils.progress.Interrupted`` raised inside a node (the cooperative
    sampler interrupt) propagates unwrapped so callers can distinguish
    "interrupted" from "failed".

    ``preseed`` force-seeds node results AFTER cache snapshotting — the
    stage hand-off hook (``carve_stages``): a downstream stage's host
    injects the upstream stage's content-addressed boundary outputs so the
    postorder short-circuits the already-executed prefix. Preseeded values
    win over cached ones for this run and are banked back under the node's
    signature like any other result.
    """
    from .nodes import NODE_CLASS_MAPPINGS

    classes: dict[str, type] = dict(NODE_CLASS_MAPPINGS)
    classes.update(class_mappings or {})

    if isinstance(workflow, (str, os.PathLike)):
        with open(workflow) as f:
            workflow = json.load(f)
    if not isinstance(workflow, dict):
        raise WorkflowError(f"workflow must be a dict, got {type(workflow).__name__}")
    graph = {str(k): v for k, v in workflow.items()}

    cache = outputs if isinstance(outputs, WorkflowCache) else None
    results: dict[str, tuple] = {} if cache is not None else dict(outputs or {})

    def node_class(nid: str) -> tuple[dict, type]:
        spec = graph.get(nid)
        if spec is None:
            raise WorkflowError(f"link references unknown node id {nid!r}")
        if not isinstance(spec, dict):
            raise WorkflowError(
                f"node {nid}: spec must be a dict with class_type/inputs, "
                f"got {type(spec).__name__}"
            )
        cls = classes.get(spec.get("class_type"))
        if cls is None:
            raise WorkflowError(
                f"node {nid}: unknown class_type {spec.get('class_type')!r} "
                f"(registered: {sorted(classes)})"
            )
        return spec, cls

    def link_inputs(spec: dict, cls: type) -> tuple[dict[str, tuple[str, int]], dict[str, str]]:
        """(links, hidden): which inputs take their value from another node's
        output, plus the host-injected hidden group.

        ComfyUI semantics: any link-shaped value is a link, even into declared
        primitive widgets — gated on the referenced id naming a graph node so
        a genuine 2-list literal into a widget stays a literal."""
        wires, declared, hidden = _wire_inputs(cls)
        links: dict[str, tuple[str, int]] = {}
        for name, v in (spec.get("inputs") or {}).items():
            if _is_link(v) and (
                name in wires or name not in declared or str(v[0]) in graph
            ):
                links[name] = (str(v[0]), int(v[1]))
        return links, hidden

    def postorder(root: str, is_done, visit) -> None:
        """Iterative post-order DFS over link dependencies — exported graphs
        can be thousands of nodes deep, so Python recursion would hit the
        interpreter limit and surface as RecursionError instead of a
        WorkflowError. ``is_done(nid)`` short-circuits already-computed nodes;
        ``visit(nid, spec, cls, links)`` runs once per node after its deps.
        Each frame caches (spec, cls, links) at expansion so INPUT_TYPES isn't
        re-derived at visit time. Shared by execution and the cache-mode
        signature pass — one traversal, one cycle-detection contract."""
        stack: list[list] = [[root, None]]
        path: list[str] = []  # gray nodes in order, for a readable cycle message
        on_path: set[str] = set()
        while stack:
            nid, resolved = stack[-1]
            if resolved is None:
                if is_done(nid):
                    stack.pop()
                    continue
                if nid in on_path:
                    raise WorkflowError(
                        f"cycle in workflow: {' -> '.join(path)} -> {nid}"
                    )
                spec, cls = node_class(nid)
                links, hidden = link_inputs(spec, cls)
                stack[-1][1] = (spec, cls, links, hidden)
                path.append(nid)
                on_path.add(nid)
                deps = dict.fromkeys(dep for dep, _ in links.values())
                for dep in reversed(list(deps)):
                    if not is_done(dep):
                        stack.append([dep, None])
                continue
            spec, cls, links, hidden = resolved
            visit(nid, spec, cls, links, hidden)
            on_path.discard(nid)
            path.pop()
            stack.pop()

    def compute_signatures() -> dict[str, str]:
        """Per-node content signature over (class_type, literal inputs,
        upstream signatures), over the whole graph regardless of cache state,
        so staleness of cached entries is detectable. Raises on cycles (cache
        mode's documented contract)."""
        import hashlib

        sigs: dict[str, str] = {}

        def visit(nid, spec, cls, links, hidden):
            canon: dict[str, Any] = {}
            for name, v in (spec.get("inputs") or {}).items():
                if name in links:
                    dep, idx = links[name]
                    canon[name] = ["__link__", sigs[dep], idx]
                else:
                    canon[name] = v
            blob = json.dumps(
                [spec.get("class_type"), canon], sort_keys=True, default=repr
            )
            sigs[nid] = hashlib.sha1(blob.encode()).hexdigest()

        for root in graph:
            postorder(root, sigs.__contains__, visit)
        return sigs

    if cache is not None:
        sigs = compute_signatures()
        # Evict-and-copy under one lock hold: this run executes against its
        # own consistent snapshot; concurrent runs (the multi-worker server)
        # merge back at completion instead of mutating shared state mid-run.
        results = cache.snapshot(sigs)
    if preseed:
        results.update(
            {str(k): tuple(v) for k, v in preseed.items() if str(k) in graph}
        )
    if on_cached is not None:
        cached = sorted(nid for nid in graph if nid in results)
        if cached:
            on_cached(cached)

    def exec_visit(nid, spec, cls, links, hidden):
        kwargs: dict[str, Any] = {}
        for name, v in (spec.get("inputs") or {}).items():
            if name in links:
                dep, idx = links[name]
                upstream = results[dep]
                if idx < 0 or idx >= len(upstream):
                    raise WorkflowError(
                        f"node {nid}: input {name!r} wants output {idx} of "
                        f"node {dep}, which has {len(upstream)} output(s) "
                        "(indices must be non-negative)"
                    )
                kwargs[name] = upstream[idx]
            else:
                kwargs[name] = v
        # Host-injected hidden values are applied LAST: ComfyUI's executor
        # lets hidden win over same-named graph inputs (a user typing a text
        # value into "prompt" must not corrupt the embedded workflow).
        for name, typ in hidden.items():
            if typ == "PROMPT":
                kwargs[name] = graph
            elif typ == "UNIQUE_ID":
                kwargs[name] = nid
            else:
                kwargs[name] = None
        # Cooperative interrupt at node granularity (ComfyUI checks between
        # nodes too, not only between sampler steps): a Cancel landing inside
        # a non-sampler node stops the graph before the NEXT node runs.
        check_interrupt(f"before node {nid}")
        if on_node is not None:
            on_node(nid)
        fn = getattr(cls(), cls.FUNCTION)
        try:
            # One workflow-node span per executed node (cached nodes never
            # reach here) — the graph layer of the per-prompt timeline; the
            # prompt_id correlation rides the thread's progress scope.
            ct = str(spec.get("class_type") or "")
            t0_node = _time.monotonic() if slo.enabled() else 0.0
            with tracing.span(
                "workflow-node", cat="graph", node=nid,
                class_type=spec.get("class_type"),
            ):
                out = fn(**kwargs)
            if slo.enabled():
                # SLO stage decomposition by node class: sampler nodes are
                # the EVAL stage (their wall includes the in-lane residency;
                # lane_wait is observed separately at the serving bucket),
                # decode nodes the DECODE stage — same boundary the
                # workflow-node span measures, one clock, two views.
                if "Decode" in ct:
                    slo.observe_stage(
                        "decode", _time.monotonic() - t0_node
                    )
                elif "Sampler" in ct:
                    slo.observe_stage(
                        "eval", _time.monotonic() - t0_node
                    )
                elif "TextEncode" in ct:
                    # The ENCODE stage (round 17): text-encode node wall —
                    # the stage the content-addressed embed cache collapses
                    # (a hit is a dict lookup; the stage histogram is where
                    # that collapse becomes visible next to eval).
                    slo.observe_stage(
                        "encode", _time.monotonic() - t0_node
                    )
        except (WorkflowError, Interrupted):
            raise
        except Exception as e:
            raise WorkflowError(
                f"node {nid} ({spec.get('class_type')}): {type(e).__name__}: {e}"
            ) from e
        if not isinstance(out, tuple):
            out = (out,)
        results[nid] = out

    try:
        for nid in graph:
            postorder(nid, results.__contains__, exec_visit)
    finally:
        if cache is not None:
            # Merge even on error/interrupt: nodes that DID complete (a slow
            # checkpoint load before a Cancel) are valid for their signatures
            # and stay warm — the reference's keep-loaded behavior across a
            # cancelled prompt.
            cache.merge(
                {nid: results[nid] for nid in graph if nid in results}, sigs
            )
    return results


def main(argv: list[str] | None = None) -> None:
    """``python -m comfyui_parallelanything_tpu.host workflow.json`` — run a
    workflow file and print each node's output types."""
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        # palint: allow[observability] __main__ CLI usage line
        print("usage: python -m comfyui_parallelanything_tpu.host <workflow.json>",
              file=sys.stderr)
        raise SystemExit(2)
    results = run_workflow(argv[0])
    for nid, out in results.items():
        # palint: allow[observability] __main__ CLI result echo
        print(f"{nid}: {tuple(type(o).__name__ for o in out)}")


if __name__ == "__main__":
    main()
